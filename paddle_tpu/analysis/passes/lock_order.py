"""lock-order: whole-program lock-acquisition graph — cycles, re-entry,
and fields written both with and without their lock.

The runtime takes real locks on real threads: the serving engine step
loop, the router's failover path, the watchdog, the flight recorder's
listener, the program store's persist path. A deadlock here doesn't
crash — it hangs a replica until the watchdog's 503 fires, which is
exactly the failure mode that is miserable to reproduce and trivial to
prevent statically.

This pass builds ONE acquisition graph across the whole lint target set
(the PR-11 version was per-class and one-hop):

- lock nodes are `Class.attr` for instance locks (`self.X =
  threading.Lock()/RLock()/Condition()` — the sanitized wrappers from
  `analysis.runtime.concurrency` keep the same constructor names) and
  `module.var` for module-level locks, matching the names the runtime
  sanitizer stamps on its observed edges;
- `with self.X:` / `with module_lock:` under other held locks adds
  graph edges; a call under a held lock adds edges to every lock the
  callee may TRANSITIVELY acquire (fixed-point closure over the
  program's call graph — call targets resolve by `self.m()` within the
  class, bare names within/through `from x import y` imports, and
  `obj.m()` by unique-name match program-wide, skipping builtin
  container/primitive method names so `self._events.append(...)` never
  aliases `EventLog.append`);
- a runtime-edges artifact (`analysis.runtime.concurrency.export_edges`
  → ``--runtime-edges`` / ``PADDLE_LINT_RUNTIME_EDGES``) merges
  observed edges the AST cannot see (attribute-chained locks, callback
  indirection) into the same graph before cycle detection;
- reported: directed cycles (two code paths take the same locks in
  opposite orders — the witness is the static acquire that closes the
  cycle, or the artifact itself for runtime-only cycles), re-entry on a
  non-reentrant Lock (direct or via self-call chains), and fields
  written BOTH inside a `with self.X` block and outside any lock
  (outside ``__init__``) — the shape of "someone forgot the lock on
  one path".

Nested function bodies are treated as separate execution contexts (a
closure may run on another thread), so a lock held at definition site is
not assumed held inside them, and a closure's own acquisitions are not
attributed to the function that merely defines it.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

_LOCK_CTORS = frozenset(('Lock', 'RLock', 'Condition'))

#: method names never used for unique-name call resolution: builtin
#: container / primitive / file-ish methods shadow real methods
#: constantly (`self._events.append` is a deque, not EventLog.append)
_ATTR_SKIP = frozenset(
    [n for t in (list, dict, set, frozenset, str, bytes, tuple)
     for n in dir(t)]
    + ['append', 'appendleft', 'popleft', 'acquire', 'release', 'wait',
       'wait_for', 'notify', 'notify_all', 'locked', 'put', 'get_nowait',
       'write', 'read', 'close', 'flush', 'start', 'cancel', 'set',
       'is_set', 'submit', 'step', 'run', 'stop', 'stats', 'snapshot',
       'emit', 'observe', 'inc', 'dec', 'labels', 'value', 'mark'])

# -- runtime-edge artifact wiring (CLI --runtime-edges / env var) -----------
_runtime_edges_path: List[Optional[str]] = [None]


def set_runtime_edges_path(path: Optional[str]):
    """CLI hook: point the pass at an `export_edges` JSON artifact."""
    _runtime_edges_path[0] = path


def runtime_edges_path() -> Optional[str]:
    if _runtime_edges_path[0]:
        return _runtime_edges_path[0]
    return os.environ.get('PADDLE_LINT_RUNTIME_EDGES') or None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == 'self':
        return node.attr
    return None


class _Func:
    """One analyzed function/method and what it does with locks."""

    __slots__ = ('module', 'cls', 'name', 'sf', 'node', 'acquires',
                 'acq_under', 'calls', 'calls_under', 'locks_all')

    def __init__(self, module: str, cls: Optional[str], name: str,
                 sf: SourceFile, node: ast.AST):
        self.module = module
        self.cls = cls
        self.name = name
        self.sf = sf
        self.node = node
        self.acquires: Set[str] = set()          # direct, own body only
        # (held tuple, lock node, With node)
        self.acq_under: List[Tuple[Tuple[str, ...], str, ast.AST]] = []
        self.calls: List[Tuple[str, str]] = []   # (kind, name)
        # (held tuple, (kind, name), Call node)
        self.calls_under: List[
            Tuple[Tuple[str, ...], Tuple[str, str], ast.AST]] = []
        self.locks_all: Set[str] = set()         # transitive closure

    @property
    def qual(self) -> str:
        return (f'{self.module}::{self.cls}.{self.name}' if self.cls
                else f'{self.module}::{self.name}')


class _ClassInfo:
    def __init__(self, module: str, node: ast.ClassDef,
                 sf: Optional[SourceFile] = None):
        self.module = module
        self.node = node
        self.sf = sf
        self.locks: Dict[str, str] = {}          # attr -> ctor kind
        # attr -> list of (held frozenset, method name, witness node)
        self.writes: Dict[str, List[Tuple[frozenset, str, ast.AST]]] = {}


class _Program:
    """Whole-target-set model: every lock, every function, one graph."""

    def __init__(self):
        self.files: List[SourceFile] = []
        self.classes: Dict[Tuple[str, str], _ClassInfo] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}  # mod -> var->kind
        self.funcs: Dict[Tuple[str, Optional[str], str], _Func] = {}
        self.by_name: Dict[str, List[_Func]] = {}
        self.imports: Dict[str, Dict[str, str]] = {}   # mod -> name->mod
        self.lock_kinds: Dict[str, str] = {}           # node -> ctor kind
        self.reentries: List[Tuple[str, ast.AST, SourceFile]] = []

    # -- collection ----------------------------------------------------
    def collect(self, sf: SourceFile):
        self.files.append(sf)
        module = os.path.splitext(os.path.basename(sf.rel))[0]
        mlocks = self.module_locks.setdefault(module, {})
        imports = self.imports.setdefault(module, {})
        for stmt in sf.tree.body:
            if isinstance(stmt, ast.ImportFrom) and stmt.module:
                src = stmt.module.rsplit('.', 1)[-1]
                for alias in stmt.names:
                    imports[alias.asname or alias.name] = src
            elif isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                seg = _util.last_segment(_util.call_name(stmt.value))
                if seg in _LOCK_CTORS:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            mlocks[t.id] = seg
                            self.lock_kinds[f'{module}.{t.id}'] = seg
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                self._collect_class(sf, module, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and isinstance(getattr(node, 'parent', None),
                                   ast.Module):
                self._add_func(sf, module, None, node)

    def _collect_class(self, sf: SourceFile, module: str,
                       cls: ast.ClassDef):
        info = _ClassInfo(module, cls, sf)
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for m in methods:
            for n in ast.walk(m):
                attrs = _util.assigned_attr_names(n)
                if not attrs or not isinstance(n, ast.Assign):
                    continue
                seg = _util.last_segment(
                    _util.call_name(n.value)) \
                    if isinstance(n.value, ast.Call) else None
                if seg in _LOCK_CTORS:
                    for a in attrs:
                        info.locks[a] = seg
                        self.lock_kinds[f'{cls.name}.{a}'] = seg
        self.classes[(module, cls.name)] = info
        for m in methods:
            self._add_func(sf, module, cls.name, m, info)

    def _add_func(self, sf: SourceFile, module: str, cls: Optional[str],
                  node, info: Optional[_ClassInfo] = None):
        f = _Func(module, cls, node.name, sf, node)
        self.funcs[(module, cls, node.name)] = f
        self.by_name.setdefault(node.name, []).append(f)
        self._walk(f, info, node.body, (),
                   in_init=(cls is not None and node.name == '__init__'))

    def _walk(self, f: _Func, info: Optional[_ClassInfo], body,
              held: Tuple[str, ...], in_init: bool):
        for node in body:
            self._walk_stmt(f, info, node, held, in_init)

    def _walk_stmt(self, f: _Func, info: Optional[_ClassInfo], node,
                   held: Tuple[str, ...], in_init: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # separate execution context: a closure may run on another
            # thread; its acquisitions are not the definer's either
            nested = _Func(f.module, f.cls, node.name, f.sf, node)
            self._walk(nested, info, node.body, (), in_init)
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                lock = self._lock_node(f, info, item.context_expr)
                if lock is None:
                    continue
                f.acquires.add(lock)
                if lock in new_held \
                        and self.lock_kinds.get(lock) == 'Lock':
                    self.reentries.append((lock, node, f.sf))
                f.acq_under.append((new_held, lock, node))
                new_held = new_held + (lock,)
            self._walk(f, info, node.body, new_held, in_init)
            return
        if info is not None and not in_init:
            for a in _util.assigned_attr_names(node):
                if a not in info.locks:
                    info.writes.setdefault(a, []).append(
                        (frozenset(held), f.name, node))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                ref = self._callee_ref(child.func)
                if ref is not None:
                    f.calls.append(ref)
                    if held:
                        f.calls_under.append((held, ref, child))
            self._walk_stmt(f, info, child, held, in_init)

    def _lock_node(self, f: _Func, info: Optional[_ClassInfo],
                   expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            if info is not None and attr in info.locks:
                return f'{info.node.name}.{attr}'
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks.get(f.module, ()):
                return f'{f.module}.{expr.id}'
        return None

    @staticmethod
    def _callee_ref(func: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == 'self':
                return ('self', func.attr)
            return ('attr', func.attr)
        if isinstance(func, ast.Name):
            return ('bare', func.id)
        return None

    # -- resolution + closure ------------------------------------------
    def _resolve(self, f: _Func, ref: Tuple[str, str]) -> List[_Func]:
        kind, name = ref
        if kind == 'self' and f.cls is not None:
            g = self.funcs.get((f.module, f.cls, name))
            return [g] if g is not None else []
        if kind == 'bare':
            g = self.funcs.get((f.module, None, name))
            if g is not None:
                return [g]
            src = self.imports.get(f.module, {}).get(name)
            if src is not None:
                g = self.funcs.get((src, None, name))
                return [g] if g is not None else []
            return []
        if kind == 'attr':
            if name in _ATTR_SKIP or name.startswith('__'):
                return []
            cands = [g for g in self.by_name.get(name, ())
                     if g.locks_all]
            # unique-name match only: ambiguity means no resolution (a
            # wrong guess here turns into a phantom deadlock report)
            return cands if len(cands) == 1 else []
        return []

    def close_over_calls(self):
        """Fixed point: every function's transitive lock set. Monotone
        (sets only grow), so it terminates; attr-resolution re-checks
        uniqueness each round against the current estimate."""
        for f in self.funcs.values():
            f.locks_all = set(f.acquires)
        changed = True
        while changed:
            changed = False
            for f in self.funcs.values():
                new = set(f.locks_all)
                for ref in f.calls:
                    for g in self._resolve(f, ref):
                        new |= g.locks_all
                if new != f.locks_all:
                    f.locks_all = new
                    changed = True


@register_pass
class LockOrderPass(AnalysisPass):
    name = 'lock-order'
    description = ('whole-program lock-acquisition graph (interprocedural'
                   ' + runtime-observed edges): AB/BA cycles, re-entry on'
                   ' non-reentrant locks, fields written both with and '
                   'without their lock')

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        prog = _Program()
        for sf in files:
            prog.collect(sf)
        prog.close_over_calls()
        findings: List[Finding] = []

        # direct re-entry witnessed while walking
        reentries = list(prog.reentries)

        # edges: (a, b) -> (witness sf, witness node, via)
        edges: Dict[Tuple[str, str], Tuple[Optional[SourceFile],
                                           Optional[ast.AST], str]] = {}
        for f in prog.funcs.values():
            for held, lock, node in f.acq_under:
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock), (f.sf, node, 'static'))
            for held, ref, node in f.calls_under:
                targets = prog._resolve(f, ref)
                for g in targets:
                    for lk in g.locks_all:
                        if lk in held:
                            # same lock reached under itself: a certain
                            # self-deadlock only when the call stays on
                            # this object (self.*); for foreign objects
                            # it may be a sibling instance's lock
                            if ref[0] == 'self' \
                                    and prog.lock_kinds.get(lk) == 'Lock':
                                reentries.append((lk, node, f.sf))
                            continue
                        for h in held:
                            if h != lk:
                                edges.setdefault(
                                    (h, lk), (f.sf, node, 'static'))

        # merge runtime-observed edges (the sanitizer's JSON artifact)
        runtime_nodes: Set[str] = set()
        path = runtime_edges_path()
        if path:
            from ..runtime.concurrency import load_edges
            for e in load_edges(path):
                a, b = str(e['from']), str(e['to'])
                runtime_nodes.update((a, b))
                edges.setdefault((a, b), (None, None, 'runtime'))

        for lock, node, sf in reentries:
            findings.append(Finding(
                pass_name=self.name, path=sf.rel,
                line=getattr(node, 'lineno', 0),
                col=getattr(node, 'col_offset', 0),
                message=(f're-entry on non-reentrant {lock} '
                         f'(threading.Lock) — self-deadlock; use RLock '
                         f'or restructure'),
                scope=_scope(node)))

        findings.extend(self._cycle_findings(edges, path))
        findings.extend(self._write_findings(prog))
        return findings

    # -- cycles --------------------------------------------------------
    def _cycle_findings(self, edges, artifact_path) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        findings: List[Finding] = []
        seen_canon: Set[Tuple[str, ...]] = set()

        def emit(cycle: Tuple[str, ...]):
            i = cycle.index(min(cycle))
            canon = cycle[i:] + cycle[:i]
            if canon in seen_canon:
                return
            seen_canon.add(canon)
            pairs = list(zip(canon, canon[1:] + (canon[0],)))
            vias = {edges[p][2] for p in pairs if p in edges}
            witness = None
            for p in pairs:
                w = edges.get(p)
                if w is not None and w[0] is not None:
                    witness = w
                    break
            pretty = ' -> '.join(canon + (canon[0],))
            note = (' (includes runtime-observed edges)'
                    if 'runtime' in vias else '')
            msg = (f'lock-order cycle: {pretty} — two paths take these '
                   f'locks in opposite orders; pick one global order or '
                   f'collapse to a single lock{note}')
            if witness is not None:
                sf, node, _ = witness
                findings.append(Finding(
                    pass_name=self.name, path=sf.rel,
                    line=getattr(node, 'lineno', 0),
                    col=getattr(node, 'col_offset', 0),
                    message=msg, scope=_scope(node)))
            else:
                findings.append(Finding(
                    pass_name=self.name,
                    path=str(artifact_path or '<runtime-edges>'),
                    line=0, col=0, message=msg, scope='<runtime>'))

        def dfs(start: str, cur: str, path: Tuple[str, ...]):
            for nxt in sorted(graph.get(cur, ())):
                if nxt == start:
                    emit(path)
                elif nxt not in path:
                    dfs(start, nxt, path + (nxt,))

        for node in sorted(graph):
            dfs(node, node, (node,))
        return findings

    # -- per-class write discipline (unchanged semantics) --------------
    def _write_findings(self, prog: _Program) -> List[Finding]:
        findings: List[Finding] = []
        for (module, cls), info in sorted(prog.classes.items()):
            if not info.locks:
                continue
            sf = info.sf
            if sf is None:
                continue
            for attr, writes in sorted(info.writes.items()):
                locked = {lk for held, _, _ in writes for lk in held}
                unlocked = [(m, w) for held, m, w in writes if not held]
                if locked and unlocked:
                    m, w = unlocked[0]
                    findings.append(Finding(
                        pass_name=self.name, path=sf.rel,
                        line=getattr(w, 'lineno', 0),
                        col=getattr(w, 'col_offset', 0),
                        message=(
                            f'{cls}.{attr} is written under '
                            f'{sorted(locked)} elsewhere but without a '
                            f'lock in `{m}` — torn/racy writes; take '
                            f'the lock on every write path'),
                        scope=_scope(w)))
        return findings


def _scope(node: ast.AST) -> str:
    from ..core import enclosing_scope
    return enclosing_scope(node)
