"""lock-order: lock-acquisition cycles and fields written both with and
without their lock.

The runtime takes real locks on real threads: the serving engine step
loop, the router's failover path, the watchdog, the flight recorder's
listener, the program store's persist path. A deadlock here doesn't
crash — it hangs a replica until the watchdog's 503 fires, which is
exactly the failure mode that is miserable to reproduce and trivial to
prevent statically.

Per class, this pass:

- collects lock attributes (`self.X = threading.Lock()/RLock()/
  Condition()`);
- builds the acquisition graph from `with self.X:` blocks — a nested
  `with self.Y:` adds edge X->Y, and a call to `self.m()` inside the
  block adds X->Z for every lock Z that method `m` acquires (one-hop
  interprocedural);
- flags cycles in that graph (two code paths taking the same pair of
  locks in opposite orders) and re-entry on a non-reentrant Lock;
- flags attributes written BOTH inside a `with self.X` block and
  outside any lock (outside ``__init__``) — the shape of "someone
  forgot the lock on one path".

Nested function bodies are treated as separate execution contexts (a
closure may run on another thread), so a lock held at definition site
is not assumed held inside them.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

_LOCK_CTORS = frozenset(('Lock', 'RLock', 'Condition'))


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == 'self':
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.locks: Dict[str, str] = {}        # attr -> ctor kind
        # (held_lock, acquired_lock) -> witness node
        self.edges: Dict[Tuple[str, str], ast.AST] = {}
        self.reentry: List[Tuple[str, ast.AST]] = []
        # method -> set of locks it acquires anywhere
        self.method_locks: Dict[str, Set[str]] = {}
        # (held_locks, callee, witness) deferred for one-hop resolution
        self.calls_under_lock: List[Tuple[Tuple[str, ...], str, ast.AST]] = []
        # attr -> list of (held_locks frozenset, method, witness)
        self.writes: Dict[str, List[Tuple[frozenset, str, ast.AST]]] = {}


@register_pass
class LockOrderPass(AnalysisPass):
    name = 'lock-order'
    description = ('lock-acquisition cycles across `with self._lock` '
                   'sites, re-entry on non-reentrant locks, and fields '
                   'written both with and without their lock')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                info = self._analyze_class(node)
                if info.locks:
                    findings.extend(self._report(sf, info))
        return findings

    # -- per-class analysis -------------------------------------------------

    def _analyze_class(self, cls: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(cls)
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for m in methods:
            for n in ast.walk(m):
                attrs = _util.assigned_attr_names(n)
                if not attrs or not isinstance(n, ast.Assign):
                    continue
                seg = _util.last_segment(
                    _util.call_name(n.value)) \
                    if isinstance(n.value, ast.Call) else None
                if seg in _LOCK_CTORS:
                    for a in attrs:
                        info.locks[a] = seg
        if not info.locks:
            return info
        for m in methods:
            acquired: Set[str] = set()
            self._walk_method(info, m, m.body, (), acquired,
                              in_init=(m.name == '__init__'))
            info.method_locks[m.name] = acquired
        # one-hop interprocedural: call under lock -> callee's locks
        for held, callee, witness in info.calls_under_lock:
            for lk in info.method_locks.get(callee, ()):
                for h in held:
                    if h != lk:
                        info.edges.setdefault((h, lk), witness)
                    elif info.locks.get(lk) == 'Lock':
                        info.reentry.append((lk, witness))
        return info

    def _walk_method(self, info: _ClassInfo, method, body,
                     held: Tuple[str, ...], acquired: Set[str],
                     in_init: bool):
        for node in body:
            self._walk_stmt(info, method, node, held, acquired, in_init)

    def _walk_stmt(self, info: _ClassInfo, method, node,
                   held: Tuple[str, ...], acquired: Set[str],
                   in_init: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # separate execution context: no lock assumed held
            self._walk_method(info, method, node.body, (), acquired,
                              in_init)
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr in info.locks:
                    acquired.add(attr)
                    if attr in new_held and info.locks[attr] == 'Lock':
                        info.reentry.append((attr, node))
                    for h in new_held:
                        if h != attr:
                            info.edges.setdefault((h, attr), node)
                    new_held = new_held + (attr,)
            self._walk_method(info, method, node.body, new_held, acquired,
                              in_init)
            return
        # record attr writes + calls, then recurse through control flow
        if not in_init:
            for a in _util.assigned_attr_names(node):
                if a not in info.locks:
                    info.writes.setdefault(a, []).append(
                        (frozenset(held), method.name, node))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call) and held:
                func = child.func
                if isinstance(func, ast.Attribute):
                    callee_self = _self_attr(func)
                    if callee_self:
                        info.calls_under_lock.append(
                            (held, callee_self, child))
            self._walk_stmt(info, method, child, held, acquired, in_init)

    # -- reporting ----------------------------------------------------------

    def _report(self, sf: SourceFile, info: _ClassInfo) -> List[Finding]:
        findings: List[Finding] = []
        cls = info.node.name
        for cycle, witness in self._find_cycles(info.edges):
            pretty = ' -> '.join(cycle + (cycle[0],))
            findings.append(self.finding(
                sf, witness,
                f'lock-order cycle in {cls}: {pretty} — two paths take '
                f'these locks in opposite orders; pick one global order '
                f'or collapse to a single lock'))
        for lk, witness in info.reentry:
            findings.append(self.finding(
                sf, witness,
                f're-entry on non-reentrant {cls}.{lk} '
                f'(threading.Lock) — self-deadlock; use RLock or '
                f'restructure'))
        for attr, writes in sorted(info.writes.items()):
            locked = {lk for held, _, _ in writes for lk in held}
            unlocked = [(m, w) for held, m, w in writes if not held]
            if locked and unlocked:
                m, w = unlocked[0]
                findings.append(self.finding(
                    sf, w,
                    f'{cls}.{attr} is written under '
                    f'{sorted(locked)} elsewhere but without a lock in '
                    f'`{m}` — torn/racy writes; take the lock on every '
                    f'write path'))
        return findings

    def _find_cycles(self, edges: Dict[Tuple[str, str], ast.AST]):
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        cycles: List[Tuple[Tuple[str, ...], ast.AST]] = []
        seen_canon: Set[Tuple[str, ...]] = set()

        def dfs(start: str, cur: str, path: Tuple[str, ...]):
            for nxt in sorted(graph.get(cur, ())):
                if nxt == start:
                    cyc = path
                    # canonical rotation so each cycle reports once
                    i = cyc.index(min(cyc))
                    canon = cyc[i:] + cyc[:i]
                    if canon not in seen_canon:
                        seen_canon.add(canon)
                        cycles.append(
                            (canon, edges[(cur, start)]))
                elif nxt not in path:
                    dfs(start, nxt, path + (nxt,))

        for node in sorted(graph):
            dfs(node, node, (node,))
        return cycles
