"""swallowed-exception: broad handlers that eat errors without a trace.

The PR 3 bug class: `CheckpointManager`'s async writer thread wrapped its
body in `except Exception: pass` — a failed checkpoint save surfaced
*fourteen runs later* as a restore from a step that was never written.
The rule: a bare/broad except (`except:`, `except Exception`,
`except BaseException`) must do at least one of

- re-raise (bare ``raise`` or ``raise X``),
- *use* the caught exception object (stored for a later re-raise,
  attached to a handle, classified, returned as a value...),
- log it (`warnings.warn`, `logging`-style `.warning/.error/
  .exception(...)`, `print`),
- emit a typed event (`emit(...)` / `*.emit(...)`),
- count it (`...inc(...)`, `count_suppressed(site)` — the
  `paddle_suppressed_errors_total{site}` counter).

Narrow handlers (`except KeyError:`) are not this pass's business —
catching a specific expected error silently is a normal control-flow
idiom. Intentional broad swallows carry
`# paddle-lint: disable=swallowed-exception -- <why>` at the handler.
"""
from __future__ import annotations

import ast
from typing import List, Set

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

_BROAD = frozenset(('Exception', 'BaseException'))

#: call last-segments that count as "the error left a trace"
_HANDLING_CALLS = frozenset((
    'warn', 'warning', 'error', 'exception', 'critical', 'info', 'debug',
    'log', 'print', 'print_exc', 'emit', 'inc', 'observe',
    'count_suppressed', 'note_fallback', 'declare_event',
    'record_exception', 'fail', 'set_exception',
))


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Tuple):
        return any(_util.last_segment(_util.dotted_name(el)) in _BROAD
                   for el in t.elts)
    return _util.last_segment(_util.dotted_name(t)) in _BROAD


def _handled(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if bound and isinstance(node, ast.Name) and node.id == bound:
            # the exception object flows somewhere: stored, classified,
            # re-raised later, attached to a result — not swallowed
            return True
        if isinstance(node, ast.Call):
            # attr lookup directly: `reg.counter(...).inc()` has a Call,
            # not a Name, at the root of its attribute chain
            seg = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else None)
            if seg and seg.lstrip('_') in _HANDLING_CALLS:
                return True
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            # `failures += 1` in a handler is a hand-rolled error counter
            return True
    return False


@register_pass
class SwallowedExceptionPass(AnalysisPass):
    name = 'swallowed-exception'
    description = ('bare/broad except blocks that neither re-raise, use '
                   'the exception, log, emit an event, nor increment a '
                   'counter — errors must leave a trace')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _handled(node):
                continue
            what = ('bare `except:`' if node.type is None else
                    f'`except '
                    f'{_util.last_segment(_util.dotted_name(node.type)) if not isinstance(node.type, ast.Tuple) else "(...broad...)"}`')
            findings.append(self.finding(
                sf, node,
                f'{what} swallows the error silently — re-raise, log, '
                f'emit a typed event, or count it into '
                f'paddle_suppressed_errors_total{{site}} '
                f'(obs.count_suppressed); silent `pass` hid a failed '
                f'checkpoint writer for 14 runs (the PR 3 bug class)'))
        return findings
