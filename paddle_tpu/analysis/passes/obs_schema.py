"""obs-schema: metrics/events schema lint (the PR 10 AST lint, grown
into a framework pass).

Contracts (unchanged from tests/test_obs_schema_lint.py, which now
drives this pass):

- every metric family literal created anywhere in the scanned tree is
  Prometheus-legal and carries the `paddle_` namespace;
- every metric family has a non-empty HELP string at (at least) one
  creation site — tree-wide aggregation, so a bare `counter('x')`
  re-reference is fine as long as SOME site documents it;
- every `emit()`ed event-type literal is declared — either a key of the
  `EVENT_SCHEMA = {...}` dict literal (observability/events.py) or a
  module-level `declare_event('name', ...)` call; f-string names must
  match a declared prefix;
- EVENT_SCHEMA entries themselves are well-formed (legal name, non-empty
  help).

The runtime complement (undeclared emits counted into
`paddle_events_undeclared_total`) stays a runtime test — a static pass
cannot see dynamic names.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

METRIC_NAME_RE = re.compile(r'^paddle_[a-z][a-z0-9_]*$')
EVENT_NAME_RE = re.compile(r'^[a-z][a-z0-9_]*$')

_METRIC_CTORS = frozenset(('counter', 'gauge', 'histogram'))


def literal_template(node: ast.AST) -> Optional[str]:
    """A plain string literal, or an f-string reduced to a template with
    `{}` placeholders; None for anything dynamic beyond that."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append('{}')
        return ''.join(parts)
    return None


def scan_schema(files: Sequence[SourceFile]) -> Dict[str, Tuple]:
    """Declared event names -> (help, witness sf, witness node): the
    EVENT_SCHEMA dict literal plus declare_event('name', ...) calls."""
    declared: Dict[str, Tuple] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            if targets and \
                    any(isinstance(t, ast.Name) and t.id == 'EVENT_SCHEMA'
                        for t in targets) and \
                    isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    name = literal_template(k) if k is not None else None
                    if name is not None:
                        declared[name] = (literal_template(v), sf, k)
            elif isinstance(node, ast.Call) and \
                    _util.last_segment(_util.call_name(node)) == \
                    'declare_event' and node.args:
                name = literal_template(node.args[0])
                if name is not None and name not in declared:
                    help_lit = literal_template(node.args[1]) \
                        if len(node.args) > 1 else name
                    declared[name] = (help_lit, sf, node)
    return declared


def scan_metrics(files: Sequence[SourceFile]):
    """metric template -> list of (sf, node, help literal)."""
    metrics: Dict[str, List] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr in _METRIC_CTORS and node.args:
                name = literal_template(node.args[0])
                if name is None:
                    continue    # dynamic beyond f-string: can't lint
                help_lit = literal_template(node.args[1]) \
                    if len(node.args) > 1 else None
                metrics.setdefault(name, []).append((sf, node, help_lit))
    return metrics


def scan_emits(files: Sequence[SourceFile]):
    """emitted event template -> list of (sf, node)."""
    emits: Dict[str, List] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    _util.last_segment(_util.call_name(node)) == 'emit' \
                    and node.args:
                name = literal_template(node.args[0])
                if name is not None:
                    emits.setdefault(name, []).append((sf, node))
    return emits


@register_pass
class ObsSchemaPass(AnalysisPass):
    name = 'obs-schema'
    description = ('metric names Prometheus-legal + paddle_-namespaced '
                   'with HELP somewhere; every emit() literal declared in '
                   'EVENT_SCHEMA/declare_event')

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        metrics = scan_metrics(files)
        emits = scan_emits(files)
        declared = scan_schema(files)

        for name, sites in sorted(metrics.items()):
            candidate = name.replace('{}', 'x')
            if not METRIC_NAME_RE.match(candidate):
                for sf, node, _ in sites:
                    findings.append(self.finding(
                        sf, node,
                        f'metric name {name!r} violates '
                        f'^paddle_[a-z][a-z0-9_]*$'))
            if not any(h and h.strip() for _, _, h in sites):
                sf, node, _ = sites[0]
                findings.append(self.finding(
                    sf, node,
                    f'metric family {name!r} has no non-empty HELP at '
                    f'any creation site'))

        for name, sites in sorted(emits.items()):
            if '{}' in name:
                prefix = name.split('{}')[0]
                ok = any(k.startswith(prefix) for k in declared)
            else:
                ok = name in declared
            if not ok:
                for sf, node in sites:
                    findings.append(self.finding(
                        sf, node,
                        f'emit() event type {name!r} is not declared in '
                        f'EVENT_SCHEMA (observability/events.py) or via '
                        f'declare_event'))

        for name, (help_lit, sf, node) in sorted(declared.items()):
            if not EVENT_NAME_RE.match(name.replace('{}', 'x')):
                findings.append(self.finding(
                    sf, node,
                    f'EVENT_SCHEMA entry {name!r} violates '
                    f'^[a-z][a-z0-9_]*$'))
            if not (help_lit and str(help_lit).strip()):
                findings.append(self.finding(
                    sf, node,
                    f'EVENT_SCHEMA entry {name!r} has empty help'))
        return findings
