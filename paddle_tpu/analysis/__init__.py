"""paddle_tpu.analysis — JAX-aware static analysis over this repo's own
source tree, enforced in tier-1.

The bug classes that cost the most across the project's history were all
statically detectable before they shipped:

- the closure-over-tracer custom_vjp break (PR 1)  -> ``trace-hazard``
- silently swallowed async-writer exceptions (PR 3) -> ``swallowed-exception``
- the ``or``-on-falsy-``EventLog`` rerouting bug (PR 10) -> ``falsy-guard``

This package is a pluggable AST-walking lint framework (`core`) plus the
passes (`passes`) plus runtime sanitizers (`runtime` — the concurrency
sanitizer's lock wrappers and `guarded_by` lockset checker, whose
observed acquisition edges feed back into the static ``lock-order``
pass via ``--runtime-edges``).  ``python -m paddle_tpu.analysis`` runs
the full suite over ``paddle_tpu/`` and ``bench.py`` (``--stats`` adds
per-pass accounting + the stale-suppression audit);
``tests/test_analysis.py`` wires the same run into tier-1, so the tree
must lint clean modulo the committed ``baseline.json`` (grandfathered
findings, each with a reason, shrink-only).

Suppression syntax (inline, justified at the site)::

    x = arr.item()  # paddle-lint: disable=host-sync -- final d2h emit
    # paddle-lint: disable-next=falsy-guard -- operates on plain lists
    y = maybe or default
"""
from .core import (  # noqa: F401
    Finding,
    SourceFile,
    AnalysisResult,
    Baseline,
    PassRegistry,
    registered_passes,
    get_pass,
    discover_files,
    run_analysis,
    render_text,
    render_json,
    DEFAULT_BASELINE_PATH,
)
from . import passes  # noqa: F401  (registers the built-in passes)
