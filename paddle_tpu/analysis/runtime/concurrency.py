"""Runtime concurrency sanitizer: lockdep-style lock-order tracking plus
an Eraser-style lockset race checker, on instrumented lock wrappers.

The fleet layer is genuinely concurrent — HTTP scrape threads, the
watchdog, async checkpoint writers, flight-recorder listeners, and
SIGTERM drain handlers all touch the registries, stores, and engines the
driver thread mutates. The static ``lock-order`` pass sees what the AST
can prove; this module sees what actually HAPPENS:

- `Lock()` / `RLock()` / `Condition()` are drop-in wrappers around the
  `threading` primitives (the static ``raw-lock`` pass requires every
  lock allocation in the tree to come through here). Each wrapper
  carries a NAME — by convention ``Class.attr`` for instance locks and
  ``module.var`` for module-level locks, matching the node names the
  static lock-order pass derives — and, while the sanitizer is enabled,
  every acquire records into one process-global acquisition graph.
- Lock-order: acquiring B while holding A adds the edge A->B (keyed by
  lock NAME, lockdep's lock-class aggregation — every instance of
  ``SlidingWindow._lock`` is one node). An edge that closes a directed
  cycle is the classic ABBA report: two code paths somewhere in the
  process's history took the same locks in opposite orders, even if
  they never actually deadlocked on this run. The witness carries both
  held-stacks.
- Re-entry: acquiring a non-reentrant `Lock` this thread already holds
  is a CERTAIN self-deadlock, so it raises `ConcurrencySanitizerError`
  in ANY enabled mode (report-only still raises here — reporting and
  then hanging forever is not a useful posture).
- Lockset (Eraser, Savage et al. SOSP'97): fields declared
  ``field = guarded_by('_lock')`` at class level are checked on every
  attribute access. While only the allocating thread has touched the
  field (the warmup — ``__init__`` writes before the object is shared)
  nothing is checked; from the first second-thread access on, every
  access intersects the field's candidate lockset with the accessing
  thread's held set. An empty intersection with a write involved is a
  race report carrying BOTH access stacks.

Reports flow through the existing machinery: a `sanitizer_violation`
event (a flight-recorder trigger), `paddle_sanitizer_violations_total
{kind}` metrics, and — in strict mode — a `ConcurrencySanitizerError`
raised at the offending acquire/access. Tier-1's chaos gauntlets
(router failover storm, autoscaler thundering herd, hotswap
kill-mid-swap, donation sentinel trips) run under strict mode.

The observed acquisition graph exports as a JSON artifact
(`export_edges`) the static pass consumes (``--runtime-edges`` /
``PADDLE_LINT_RUNTIME_EDGES``), so dynamic-only edges — cross-class
nesting the AST cannot resolve — merge into the whole-program static
cycle check.

Modes (``FLAGS_concurrency_sanitizer`` / env, or `enable()`):
  'off'     wrappers delegate with one integer check of overhead;
  'report'  violations are counted + emitted, execution continues;
  'strict'  violations raise `ConcurrencySanitizerError`.

This module is imported by the metrics registry itself, so it imports
nothing from paddle_tpu at module scope except `flags`; observability is
reached lazily, behind a thread-local re-entrancy guard (reporting a
violation takes the very locks being sanitized).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

from ... import flags as _flags

_flags.register_flag('FLAGS_concurrency_sanitizer', 'off')

MODE_OFF, MODE_REPORT, MODE_STRICT = 0, 1, 2
_MODE_NAMES = {'off': MODE_OFF, 'report': MODE_REPORT,
               'strict': MODE_STRICT}

# single-element list: reads are one index op on the hot path
_mode = [_MODE_NAMES.get(str(_flags.flag('FLAGS_concurrency_sanitizer')),
                         MODE_OFF)]

# violation kinds (the {kind} label on paddle_sanitizer_violations_total)
KIND_LOCK_ORDER = 'lock_order_cycle'
KIND_REENTRY = 'reentry'
KIND_LOCKSET = 'lockset_race'
KINDS = (KIND_LOCK_ORDER, KIND_REENTRY, KIND_LOCKSET)

#: frames kept per witness stack (acquisition sites, not full tracebacks)
STACK_DEPTH = 6


class ConcurrencySanitizerError(RuntimeError):
    """A concurrency violation under strict mode (or a certain
    self-deadlock under any enabled mode). Carries the violation kind
    and the witness dict the report machinery recorded."""

    def __init__(self, kind: str, message: str,
                 witness: Optional[Dict[str, Any]] = None):
        self.kind = kind
        self.witness = witness or {}
        super().__init__(f'[{kind}] {message}')


class _ThreadState(threading.local):
    def __init__(self):
        self.held: List['SanitizedLock'] = []
        self.in_report = False


_tls = _ThreadState()

# process-global sanitizer state; guarded by a RAW lock — the one lock
# in the tree that cannot be sanitized with itself
_state_lock = threading.Lock()  # paddle-lint: disable=raw-lock -- the sanitizer's own state lock cannot be a sanitized lock
_graph: Dict[str, Set[str]] = {}                 # name -> successors
_edge_witness: Dict[Tuple[str, str], Dict[str, Any]] = {}
_violations: List[Dict[str, Any]] = []
_reported: Set[str] = set()                      # dedup keys


def _stack(skip: int = 2) -> List[str]:
    """Compact acquisition-site witness: 'file:line in fn' frames,
    innermost last, sanitizer frames trimmed. BOUNDED extraction
    (STACK_DEPTH frames from the caller, not the whole stack): a full
    extract_stack under a deep test-harness stack costs hundreds of
    microseconds, and witnesses are only worth capturing at report /
    new-edge time anyway."""
    frames = traceback.extract_stack(sys._getframe(skip), STACK_DEPTH)
    return [f'{os.path.basename(f.filename)}:{f.lineno} in {f.name}'
            for f in frames]


def _site(skip: int = 2) -> str:
    """One caller frame, no traceback machinery — the per-access
    bookkeeping cost the lockset checker pays on EVERY guarded access,
    so it must stay at raw-_getframe cost."""
    f = sys._getframe(skip)
    return (f'{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} '
            f'in {f.f_code.co_name}')


def _thread_label() -> str:
    t = threading.current_thread()
    return f'{t.name}({t.ident})'


def _report(kind: str, dedup_key: str, message: str,
            witness: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Record one violation (deduped per site, lockdep-style: the first
    report per cycle/field is the signal; a storm of repeats is noise).
    Returns the violation dict when it was newly reported. Raises in
    strict mode; re-entry raises in any enabled mode (callers pass
    `always_raise`)."""
    with _state_lock:
        if dedup_key in _reported:
            return None
        _reported.add(dedup_key)
        violation = {'kind': kind, 'message': message,
                     'thread': _thread_label(), **witness}
        _violations.append(violation)
    # the report machinery takes sanitized locks (registry, event log);
    # the thread-local guard keeps the sanitizer out of its own way
    _tls.in_report = True
    try:
        from ... import observability as _obs
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_sanitizer_violations_total',
                'concurrency-sanitizer violations by kind (lock-order '
                'cycle, non-reentrant re-entry, lockset race)',
                ('kind',)).labels(kind=kind).inc()
            _obs.emit('sanitizer_violation', kind=kind, message=message,
                      **{k: v for k, v in witness.items()
                         if isinstance(v, (str, int, float, list))})
    except Exception:  # paddle-lint: disable=swallowed-exception -- reporting must never mask the violation; it is already recorded in _violations
        pass
    finally:
        _tls.in_report = False
    return violation


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """Directed path src -> dst in the acquisition graph (callers hold
    _state_lock). Iterative DFS; returns the node list or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _graph.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class SanitizedLock:
    """Instrumented `threading.Lock`/`RLock`. Drop-in: acquire/release/
    locked/context manager. `name` keys the lock's CLASS in the
    acquisition graph ('Router._lock', 'donation._probe_lock')."""

    __slots__ = ('name', 'kind', '_inner')

    _REENTRANT = False

    def __init__(self, name: str = ''):
        self.name = name or f'anonymous@{id(self):x}'
        self.kind = 'RLock' if self._REENTRANT else 'Lock'
        if self._REENTRANT:
            self._inner = threading.RLock()  # paddle-lint: disable=raw-lock -- the wrapped primitive itself
        else:
            self._inner = threading.Lock()  # paddle-lint: disable=raw-lock -- the wrapped primitive itself

    # -- tracking ------------------------------------------------------
    def _before_acquire(self):
        held = _tls.held
        if not self._REENTRANT and any(h is self for h in held):
            # a certain self-deadlock: raise in ANY enabled mode —
            # "report-only" must not mean "report, then hang forever"
            v = _report(
                KIND_REENTRY, f'reentry::{self.name}::{_stack()[-1]}',
                f're-entry on non-reentrant {self.name} — this thread '
                f'already holds it; the acquire would self-deadlock',
                {'lock': self.name, 'stack': _stack()})
            raise ConcurrencySanitizerError(
                KIND_REENTRY,
                f're-entry on non-reentrant {self.name}',
                v or {'lock': self.name})
        new_edges = []
        for h in held:
            if h is self or h.name == self.name:
                # same lock class nested (two instances of the same
                # wrapper name, or an RLock re-acquire): not an order
                # edge — a self-edge would report every RLock re-entry
                # as a cycle
                continue
            with _state_lock:
                succ = _graph.setdefault(h.name, set())
                if self.name in succ:
                    continue
                succ.add(self.name)
                _edge_witness[(h.name, self.name)] = {
                    'held': h.name, 'acquired': self.name,
                    'thread': _thread_label(), 'stack': _stack(3)}
                new_edges.append(h.name)
        for src in new_edges:
            self._check_cycle(src)

    def _check_cycle(self, src: str):
        """The new edge src -> self.name just landed; a path
        self.name -> src means two orders coexist."""
        with _state_lock:
            path = _find_path(self.name, src)
            if path is None:
                return
            cycle = tuple(path)  # self.name ... src (+ back via new edge)
            i = cycle.index(min(cycle))
            canon = cycle[i:] + cycle[:i]
            witnesses = {}
            for a, b in zip(path, path[1:] + [path[0]]):
                w = _edge_witness.get((a, b))
                if w is not None:
                    witnesses[f'{a}->{b}'] = {
                        'thread': w['thread'], 'stack': w['stack']}
        pretty = ' -> '.join(canon + (canon[0],))
        v = _report(
            KIND_LOCK_ORDER, f'cycle::{"|".join(canon)}',
            f'lock-order cycle: {pretty} — two code paths take these '
            f'locks in opposite orders; pick one global order',
            {'cycle': list(canon), 'witnesses': witnesses})
        if v is not None and _mode[0] >= MODE_STRICT:
            raise ConcurrencySanitizerError(
                KIND_LOCK_ORDER, f'lock-order cycle: {pretty}', v)

    # -- the threading.Lock surface ------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _mode[0] and not _tls.in_report:
            self._before_acquire()
            got = self._inner.acquire(blocking, timeout)
            if got:
                _tls.held.append(self)
            return got
        return self._inner.acquire(blocking, timeout)

    def release(self):
        if _mode[0] and not _tls.in_report:
            held = _tls.held
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_current_thread(self) -> bool:
        """Sanitizer's view (only meaningful while enabled)."""
        return any(h is self for h in _tls.held)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f'{type(self).__name__}({self.name!r})'


class SanitizedRLock(SanitizedLock):
    __slots__ = ()
    _REENTRANT = True

    def locked(self) -> bool:
        # threading.RLock has no .locked() before 3.12; emulate via a
        # non-blocking probe (true when another thread holds it or we
        # do — callers only use this diagnostically)
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


def Lock(name: str = '') -> SanitizedLock:
    """Instrumented non-reentrant lock. Name it 'Class.attr' (instance
    locks) or 'module.var' (module-level) so runtime edges merge with
    the static lock-order graph's node names."""
    return SanitizedLock(name)


def RLock(name: str = '') -> SanitizedRLock:
    """Instrumented reentrant lock (same naming convention as Lock)."""
    return SanitizedRLock(name)


class SanitizedCondition:
    """Condition variable over a sanitized lock: acquire/release go
    through the wrapper (tracked); wait/notify delegate to a real
    `threading.Condition` built on the wrapper's inner primitive.
    While a thread is blocked in `wait()` its held-stack still lists
    the lock — it records no accesses while blocked, and holds the
    lock again the moment wait returns, so the approximation is
    sound for every check the sanitizer runs."""

    __slots__ = ('name', '_lock', '_cond')

    def __init__(self, lock: Optional[SanitizedLock] = None,
                 name: str = ''):
        if lock is None:
            lock = RLock(name=f'{name or "Condition"}.lock')
        if not isinstance(lock, SanitizedLock):
            raise TypeError(
                'SanitizedCondition needs a sanitized Lock/RLock '
                f'(got {type(lock).__name__}); allocate it via '
                'analysis.runtime.Lock/RLock')
        self.name = name or lock.name
        self._lock = lock
        self._cond = threading.Condition(lock._inner)  # paddle-lint: disable=raw-lock -- wraps the sanitized lock's own primitive

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()

    def wait(self, timeout: Optional[float] = None):
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f'SanitizedCondition({self.name!r})'


def Condition(lock: Optional[SanitizedLock] = None,
              name: str = '') -> SanitizedCondition:
    """Instrumented condition variable (see SanitizedCondition)."""
    return SanitizedCondition(lock, name=name)


# ---------------------------------------------------------------------------
# Eraser-style lockset checking: @guarded_by fields
# ---------------------------------------------------------------------------

class guarded_by:
    """Class-level field declaration: every access to the field must
    hold (one of) the named sanitized lock attribute(s)::

        class FlightRecorder:
            _steps = guarded_by('_lock', mutable=True)

    The value lives in the instance ``__dict__`` under a private slot;
    with the sanitizer off, access is one dict lookup. With it on, the
    Eraser state machine runs per (instance, field):

      virgin -> owned (allocating thread only; ``__init__`` writes
      before the object is shared are the warmup and never checked)
      -> shared from the first access by a second thread; thereafter
      EVERY access intersects the candidate lockset (initially the
      declared guard instances) with the accessing thread's held set.
      Empty intersection with a write involved = `lockset_race`,
      reported with both access stacks.

    ``mutable=True`` treats reads as writes — for container fields
    (deques, dicts) whose mutation happens through methods the
    descriptor can only see as reads.
    """

    def __init__(self, *lock_attrs: str, mutable: bool = False):
        if not lock_attrs:
            raise ValueError('guarded_by needs at least one lock attr')
        self.lock_attrs = tuple(lock_attrs)
        self.mutable = bool(mutable)
        self._name = '<unbound>'
        self._slot = None
        self._state_slot = None
        self._owner = None

    def __set_name__(self, owner, name):
        self._name = name
        self._owner = owner.__name__
        self._slot = f'_gb_value_{name}'
        self._state_slot = f'_gb_state_{name}'

    # -- the Eraser state machine --------------------------------------
    def _check(self, obj, write: bool):
        tid = threading.get_ident()
        d = obj.__dict__
        st = d.get(self._state_slot)
        # ONE frame per access (raw _getframe); the full bounded stack
        # is only extracted when a report actually fires
        site = _site(3)
        if st is None:
            st = d[self._state_slot] = {
                'first_tid': tid, 'shared': False, 'lockset': None,
                'write_seen': False, 'last': None}
        if tid != st['first_tid']:
            st['shared'] = True
        if st['shared']:
            declared = set()
            for attr in self.lock_attrs:
                lk = getattr(obj, attr, None)
                if isinstance(lk, SanitizedLock):
                    declared.add(id(lk))
            held = {id(h) for h in _tls.held}
            lockset = st['lockset']
            if lockset is None:
                lockset = declared
            lockset &= held
            st['lockset'] = lockset
            st['write_seen'] = st['write_seen'] or write
            if not lockset and st['write_seen']:
                field = f'{self._owner}.{self._name}'
                prev = st['last']
                v = _report(
                    KIND_LOCKSET, f'lockset::{field}',
                    f'{field} accessed without its declared guard '
                    f'{self.lock_attrs} after becoming shared — '
                    f'candidate lockset is empty (a data race)',
                    {'field': field, 'guards': list(self.lock_attrs),
                     'access': 'write' if write else 'read',
                     'stack': _stack(3),      # full witness, report-time only
                     'other_access': dict(prev) if prev else None})
                if v is not None and _mode[0] >= MODE_STRICT:
                    st['last'] = {'thread': _thread_label(),
                                  'access': 'write' if write else 'read',
                                  'stack': [site]}
                    raise ConcurrencySanitizerError(
                        KIND_LOCKSET, f'lockset race on {field}', v)
        st['last'] = {'thread': _thread_label(),
                      'access': 'write' if write else 'read',
                      'stack': [site]}

    # -- descriptor protocol -------------------------------------------
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if _mode[0] and not _tls.in_report:
            self._check(obj, write=self.mutable)
        try:
            return obj.__dict__[self._slot]
        except KeyError:
            raise AttributeError(
                f'{self._owner}.{self._name} accessed before first '
                f'assignment') from None

    def __set__(self, obj, value):
        if _mode[0] and not _tls.in_report:
            self._check(obj, write=True)
        obj.__dict__[self._slot] = value

    def __delete__(self, obj):
        obj.__dict__.pop(self._slot, None)
        obj.__dict__.pop(self._state_slot, None)


# ---------------------------------------------------------------------------
# mode control + introspection
# ---------------------------------------------------------------------------

def mode() -> str:
    return {v: k for k, v in _MODE_NAMES.items()}[_mode[0]]


def enable(new_mode: str = 'report'):
    """Switch the sanitizer mode ('off' | 'report' | 'strict'); mirrors
    into FLAGS_concurrency_sanitizer."""
    if new_mode not in _MODE_NAMES:
        raise ValueError(
            f'mode must be one of {sorted(_MODE_NAMES)}, got {new_mode!r}')
    _mode[0] = _MODE_NAMES[new_mode]
    _flags.set_flags({'FLAGS_concurrency_sanitizer': new_mode})


def disable():
    enable('off')


class sanitized:
    """Context manager scoping a sanitizer mode (tests, gauntlets)::

        with concurrency.sanitized('strict'):
            run_chaos()
    """

    def __init__(self, new_mode: str = 'report'):
        self._new = new_mode
        self._prev = mode()

    def __enter__(self):
        self._prev = mode()
        enable(self._new)
        return self

    def __exit__(self, *exc):
        enable(self._prev)


def violations(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Violations recorded since the last reset (all kinds, or one)."""
    with _state_lock:
        out = list(_violations)
    if kind is not None:
        out = [v for v in out if v['kind'] == kind]
    return out


def reset():
    """Clear the acquisition graph, violation list, and report dedup —
    NOT the mode. Tests call this to isolate edge history; production
    never should (the accumulated graph IS the lockdep value)."""
    with _state_lock:
        _graph.clear()
        _edge_witness.clear()
        _violations.clear()
        _reported.clear()


def observed_edges() -> List[Dict[str, Any]]:
    """The acquisition graph as a list of {'from','to','thread','stack'}
    edge dicts (the JSON artifact's payload)."""
    with _state_lock:
        out = []
        for (a, b), w in sorted(_edge_witness.items()):
            out.append({'from': a, 'to': b, 'thread': w['thread'],
                        'stack': list(w['stack'])})
        return out


def stats() -> Dict[str, Any]:
    """Sanitizer posture + counters (debug summary / tests)."""
    with _state_lock:
        nodes = set(_graph)
        for succ in _graph.values():
            nodes |= succ
        by_kind = {k: 0 for k in KINDS}
        for v in _violations:
            by_kind[v['kind']] = by_kind.get(v['kind'], 0) + 1
        return {'mode': mode(), 'lock_classes': len(nodes),
                'edges': len(_edge_witness),
                'violations': dict(by_kind)}


def export_edges(path: str) -> str:
    """Write the observed acquisition edges as the JSON artifact the
    static lock-order pass merges (``python -m paddle_tpu.analysis
    --runtime-edges <path>``). Returns the path."""
    doc = {'version': 1, 'tool': 'paddle_tpu.analysis.runtime',
           'edges': observed_edges()}
    tmp = f'{path}.tmp'
    with open(tmp, 'w') as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return path


def load_edges(path: str) -> List[Dict[str, Any]]:
    """Read an `export_edges` artifact back (used by the static pass;
    raises on malformed input — a lint consuming garbage must say so)."""
    with open(path) as f:
        doc = json.load(f)
    edges = doc.get('edges')
    if not isinstance(edges, list):
        raise ValueError(f'{path}: not a runtime-edges artifact '
                         f'(missing "edges" list)')
    for e in edges:
        if not (isinstance(e, dict) and 'from' in e and 'to' in e):
            raise ValueError(f'{path}: malformed edge entry {e!r}')
    return edges
