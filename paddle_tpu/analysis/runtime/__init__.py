"""paddle_tpu.analysis.runtime — runtime sanitizers (the dynamic
complement to the static passes).

`concurrency` is the first: instrumented lock wrappers feeding a
process-global acquisition graph (lockdep-style cycle/re-entry
detection) plus an Eraser-style `guarded_by` lockset race checker.
Everything here is stdlib-only at import time — the metrics registry
itself allocates its lock through these wrappers.
"""
from .concurrency import (  # noqa: F401
    KIND_LOCK_ORDER,
    KIND_LOCKSET,
    KIND_REENTRY,
    KINDS,
    Condition,
    ConcurrencySanitizerError,
    Lock,
    RLock,
    SanitizedCondition,
    SanitizedLock,
    SanitizedRLock,
    disable,
    enable,
    export_edges,
    guarded_by,
    load_edges,
    mode,
    observed_edges,
    reset,
    sanitized,
    stats,
    violations,
)
