"""CLI: ``python -m paddle_tpu.analysis [targets...]``.

Exit-code contract (stable, scripted against by CI):
  0  clean (no unsuppressed/un-grandfathered findings, no stale baseline)
  1  findings (or stale baseline entries — the shrink-only rule)
  2  internal error (bad arguments, unreadable target, broken pass)
"""
from __future__ import annotations

import argparse
import sys
import traceback

from . import core


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m paddle_tpu.analysis',
        description='JAX-aware static analysis over the paddle_tpu tree')
    p.add_argument('targets', nargs='*', default=None,
                   help='files/dirs to lint (default: paddle_tpu/ bench.py)')
    p.add_argument('--format', choices=('text', 'json'), default='text')
    p.add_argument('--passes', default=None,
                   help='comma-separated subset (default: all registered)')
    p.add_argument('--baseline', default=str(core.DEFAULT_BASELINE_PATH),
                   help='baseline.json path (grandfathered findings)')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every finding, ignoring the baseline')
    p.add_argument('--list-passes', action='store_true')
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.list_passes:
            for name in core.registered_passes():
                cls = core.REGISTRY._passes[name]
                print(f'{name}: {cls.description}')
            return 0
        passes = None
        if args.passes:
            passes = [s.strip() for s in args.passes.split(',') if s.strip()]
            for name in passes:
                if name not in core.registered_passes():
                    raise KeyError(f'unknown pass {name!r}; available: '
                                   f'{core.registered_passes()}')
        baseline = None if args.no_baseline else core.Baseline.load(args.baseline)
        result = core.run_analysis(targets=args.targets or None,
                                   passes=passes, baseline=baseline)
    except Exception:
        traceback.print_exc()
        return 2
    render = core.render_json if args.format == 'json' else core.render_text
    print(render(result))
    return 0 if result.clean else 1


if __name__ == '__main__':
    sys.exit(main())
