"""CLI: ``python -m paddle_tpu.analysis [targets...]``.

Exit-code contract (stable, scripted against by CI):
  0  clean (no unsuppressed/un-grandfathered findings, no stale baseline)
  1  findings (or stale baseline entries — the shrink-only rule)
  2  internal error (bad arguments, unreadable target, broken pass)
"""
from __future__ import annotations

import argparse
import sys
import traceback

from . import core


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m paddle_tpu.analysis',
        description='JAX-aware static analysis over the paddle_tpu tree')
    p.add_argument('targets', nargs='*', default=None,
                   help='files/dirs to lint (default: paddle_tpu/ bench.py)')
    p.add_argument('--format', choices=('text', 'json'), default='text')
    p.add_argument('--passes', default=None,
                   help='comma-separated subset (default: all registered)')
    p.add_argument('--baseline', default=str(core.DEFAULT_BASELINE_PATH),
                   help='baseline.json path (grandfathered findings)')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every finding, ignoring the baseline')
    p.add_argument('--list-passes', action='store_true')
    p.add_argument('--stats', action='store_true',
                   help='per-pass finding/suppression/baseline counts + '
                        'stale-suppression audit (an inline disable '
                        'whose pass no longer fires there fails the '
                        'run, mirroring the shrink-only baseline)')
    p.add_argument('--runtime-edges', default=None, metavar='JSON',
                   help='runtime-observed lock-acquisition edges '
                        '(analysis.runtime.concurrency.export_edges '
                        'artifact) merged into the static lock-order '
                        'graph; PADDLE_LINT_RUNTIME_EDGES is the env '
                        'equivalent')
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.list_passes:
            for name in core.registered_passes():
                cls = core.REGISTRY._passes[name]
                print(f'{name}: {cls.description}')
            return 0
        passes = None
        if args.passes:
            passes = [s.strip() for s in args.passes.split(',') if s.strip()]
            for name in passes:
                if name not in core.registered_passes():
                    raise KeyError(f'unknown pass {name!r}; available: '
                                   f'{core.registered_passes()}')
        if args.runtime_edges:
            from .passes import lock_order
            lock_order.set_runtime_edges_path(args.runtime_edges)
        baseline = None if args.no_baseline else core.Baseline.load(args.baseline)
        files = core.discover_files(args.targets or None)
        result = core.run_analysis(passes=passes, baseline=baseline,
                                   files=files)
        if args.stats:
            stale = core.audit_suppressions(files, result)
            stats = core.compute_stats(result, stale, baseline)
    except Exception:
        traceback.print_exc()
        return 2
    if args.stats:
        if args.format == 'json':
            import json
            print(json.dumps(stats, indent=1))
        else:
            print(core.render_stats_text(stats))
        return 0 if stats['clean'] else 1
    render = core.render_json if args.format == 'json' else core.render_text
    print(render(result))
    return 0 if result.clean else 1


if __name__ == '__main__':
    sys.exit(main())
