"""Framework core: file discovery, pass registry, inline suppressions,
committed baseline, reporters.

Everything here is stdlib-only AST walking — the analysis modules
themselves never import jax or touch a device, so passes run (and fail)
deterministically on any box. (Invoking via ``python -m
paddle_tpu.analysis`` still executes the parent package's ``__init__``;
the analysis itself does no runtime work beyond parsing source text.)
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent

#: default lint targets, relative to the repo root
DEFAULT_TARGETS = ('paddle_tpu', 'bench.py')

#: committed grandfather list (shrink-only; see Baseline)
DEFAULT_BASELINE_PATH = pathlib.Path(__file__).resolve().parent / 'baseline.json'

_SUPPRESS_RE = re.compile(
    r'#\s*paddle-lint:\s*(disable|disable-next|disable-file)='
    r'([a-z0-9_\-, ]+?)\s*(?:--.*)?$')


# ---------------------------------------------------------------------------
# source model
# ---------------------------------------------------------------------------

class SourceFile:
    """One parsed module: path, text, lines, AST with parent links, and
    the suppression table scraped from comments."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path = REPO_ROOT):
        self.path = pathlib.Path(path)
        try:
            self.rel = self.path.resolve().relative_to(root).as_posix()
        except ValueError:
            self.rel = self.path.as_posix()
        self.text = self.path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(self.path))
        add_parents(self.tree)
        self._line_suppress: Dict[int, set] = {}
        self._file_suppress: set = set()
        #: every inline suppression comment as written: the --stats
        #: stale-suppression audit needs the SITE (which comment, which
        #: passes), not just the merged line->passes table
        self.suppress_sites: List[Dict] = []
        self._scan_suppressions()

    def _comment_lines(self):
        """(line, comment-text) for every REAL comment token. A
        suppression example inside a docstring must neither silence
        findings on its line nor count as a stale annotation in the
        --stats audit; tokenizing is the only way to tell them apart."""
        try:
            return [(tok.start[0], tok.string) for tok in
                    tokenize.generate_tokens(io.StringIO(self.text).readline)
                    if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError):
            # unparseable tail (ast.parse already succeeded, so this is
            # theoretical): fall back to the line scan
            return list(enumerate(self.lines, start=1))

    def _scan_suppressions(self):
        for i, line in self._comment_lines():
            if 'paddle-lint' not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            kind = m.group(1)
            names = {p.strip() for p in m.group(2).split(',') if p.strip()}
            if kind == 'disable':
                self._line_suppress.setdefault(i, set()).update(names)
                effective = i
            elif kind == 'disable-next':
                self._line_suppress.setdefault(i + 1, set()).update(names)
                effective = i + 1
            elif kind == 'disable-file':
                self._file_suppress.update(names)
                effective = None
            self.suppress_sites.append(
                {'comment_line': i, 'kind': kind,
                 'names': sorted(names), 'effective_line': effective})

    def suppressed(self, pass_name: str, line: int) -> bool:
        if pass_name in self._file_suppress or 'all' in self._file_suppress:
            return True
        names = self._line_suppress.get(line, ())
        return pass_name in names or 'all' in names


def add_parents(tree: ast.AST):
    """Annotate every node with a `.parent` backlink (passes walk up to
    find the enclosing function/class)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]
    return tree


def enclosing_scope(node: ast.AST) -> str:
    """Dotted qualname of the enclosing def/class chain, or '<module>'.
    Line-number free on purpose: it anchors baseline keys, which must
    survive unrelated edits above the finding."""
    parts: List[str] = []
    cur = getattr(node, 'parent', None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, 'parent', None)
    return '.'.join(reversed(parts)) if parts else '<module>'


def enclosing_function(node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef node, or None."""
    cur = getattr(node, 'parent', None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, 'parent', None)
    return None


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Finding:
    pass_name: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    scope: str = '<module>'
    #: disambiguates identical (pass, path, scope, message) findings by
    #: source order; assigned by run_analysis
    occurrence: int = 0

    @property
    def key(self) -> str:
        """Baseline identity. Deliberately excludes line/col so a finding
        keeps matching its grandfather entry when unrelated code moves it."""
        base = f'{self.pass_name}::{self.path}::{self.scope}::{self.message}'
        return base if self.occurrence == 0 else f'{base}::#{self.occurrence}'

    def render(self) -> str:
        return (f'{self.path}:{self.line}:{self.col}: '
                f'[{self.pass_name}] {self.message} (in {self.scope})')

    def as_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d['key'] = self.key
        return d


def assign_occurrences(findings: List[Finding]) -> List[Finding]:
    """Number duplicate (pass, path, scope, message) findings in source
    order so every key is unique."""
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.col,
                                               f.pass_name, f.message))
    seen: Dict[str, int] = {}
    for f in findings:
        base = f'{f.pass_name}::{f.path}::{f.scope}::{f.message}'
        f.occurrence = seen.get(base, 0)
        seen[base] = f.occurrence + 1
    return findings


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

class PassRegistry:
    def __init__(self):
        self._passes: Dict[str, type] = {}

    def register(self, cls):
        name = getattr(cls, 'name', None)
        if not name or not re.match(r'^[a-z][a-z0-9\-]*$', name):
            raise ValueError(f'pass class {cls!r} needs a kebab-case .name')
        if name in self._passes:
            raise ValueError(f'duplicate pass name {name!r}')
        self._passes[name] = cls
        return cls

    def names(self) -> List[str]:
        return sorted(self._passes)

    def create(self, name: str):
        try:
            return self._passes[name]()
        except KeyError:
            raise KeyError(
                f'unknown pass {name!r}; available: {self.names()}') from None


REGISTRY = PassRegistry()
register_pass = REGISTRY.register


def registered_passes() -> List[str]:
    return REGISTRY.names()


def get_pass(name: str):
    return REGISTRY.create(name)


class AnalysisPass:
    """Base class: override `visit_file` for per-file passes or `run`
    for passes needing the whole file set (cross-file aggregation)."""

    name = ''
    description = ''

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            out.extend(self.visit_file(sf))
        return out

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        return []

    def finding(self, sf: SourceFile, node: ast.AST, message: str) -> Finding:
        return Finding(pass_name=self.name, path=sf.rel,
                       line=getattr(node, 'lineno', 0),
                       col=getattr(node, 'col_offset', 0),
                       message=message, scope=enclosing_scope(node))


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class Baseline:
    """Committed grandfather list. Contract (the "shrink-only" rule):

    - every entry carries a human `reason`;
    - the header records `entry_count`, asserted == len(entries) both
      here and in tier-1, so growing the list is an explicit, reviewable
      diff in two places;
    - a baseline entry whose finding no longer exists is STALE and fails
      the run — fixing a grandfathered finding forces deleting its entry,
      so the list can only shrink.
    """

    def __init__(self, entries: Optional[Dict[str, str]] = None,
                 path: Optional[pathlib.Path] = None):
        self.entries: Dict[str, str] = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path=DEFAULT_BASELINE_PATH) -> 'Baseline':
        path = pathlib.Path(path)
        if not path.exists():
            return cls(path=path)
        data = json.loads(path.read_text())
        entries = {e['key']: e.get('reason', '') for e in data.get('entries', ())}
        declared = data.get('header', {}).get('entry_count')
        if declared is not None and declared != len(entries):
            raise ValueError(
                f'baseline header entry_count={declared} but file has '
                f'{len(entries)} unique entries — header and entries must '
                f'be updated together ({path})')
        missing = [k for k, r in entries.items() if not str(r).strip()]
        if missing:
            raise ValueError(
                f'baseline entries without a reason: {missing[:3]}...')
        return cls(entries, path=path)

    def save(self, path: Optional[pathlib.Path] = None):
        path = pathlib.Path(path or self.path)
        payload = {
            'header': {
                'tool': 'paddle_tpu.analysis',
                'entry_count': len(self.entries),
                'note': ('shrink-only: entries may be removed when fixed, '
                         'never added without review; stale entries fail '
                         'the run'),
            },
            'entries': [{'key': k, 'reason': v}
                        for k, v in sorted(self.entries.items())],
        }
        path.write_text(json.dumps(payload, indent=1) + '\n')

    def split(self, findings: Sequence[Finding]):
        """(new, grandfathered, stale_keys)."""
        keys = {f.key for f in findings}
        new = [f for f in findings if f.key not in self.entries]
        old = [f for f in findings if f.key in self.entries]
        stale = sorted(k for k in self.entries if k not in keys)
        return new, old, stale


# ---------------------------------------------------------------------------
# discovery + driver
# ---------------------------------------------------------------------------

def discover_files(targets: Optional[Sequence] = None,
                   root: pathlib.Path = REPO_ROOT) -> List[SourceFile]:
    paths: List[pathlib.Path] = []
    for t in (targets or DEFAULT_TARGETS):
        p = pathlib.Path(t)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            paths.extend(sorted(q for q in p.rglob('*.py')
                                if '__pycache__' not in q.parts))
        elif p.exists():
            paths.append(p)
        else:
            raise FileNotFoundError(f'lint target does not exist: {t}')
    return [SourceFile(p, root=root) for p in paths]


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]            # unsuppressed, not grandfathered
    grandfathered: List[Finding]       # matched a baseline entry
    suppressed: List[Finding]          # silenced by an inline comment
    stale_baseline: List[str]          # baseline keys with no live finding
    files_scanned: int = 0
    passes_run: Tuple[str, ...] = ()

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale_baseline

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.pass_name] = out.get(f.pass_name, 0) + 1
        return out


def run_analysis(targets: Optional[Sequence] = None,
                 passes: Optional[Sequence[str]] = None,
                 baseline: Optional[Baseline] = None,
                 root: pathlib.Path = REPO_ROOT,
                 files: Optional[Sequence[SourceFile]] = None) -> AnalysisResult:
    """Drive the configured passes over the target files and reconcile
    against the baseline. `baseline=None` means no grandfathering."""
    if files is None:
        files = discover_files(targets, root=root)
    pass_names = list(passes) if passes is not None else registered_passes()
    raw: List[Finding] = []
    for name in pass_names:
        raw.extend(REGISTRY.create(name).run(files))
    raw = assign_occurrences(raw)

    by_rel = {sf.rel: sf for sf in files}
    live, suppressed = [], []
    for f in raw:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.pass_name, f.line):
            suppressed.append(f)
        else:
            live.append(f)

    if baseline is None:
        new, old, stale = live, [], []
    else:
        new, old, stale = baseline.split(live)
    return AnalysisResult(findings=new, grandfathered=old,
                          suppressed=suppressed, stale_baseline=stale,
                          files_scanned=len(files),
                          passes_run=tuple(pass_names))


# ---------------------------------------------------------------------------
# suppression audit + stats (the --stats subcommand)
# ---------------------------------------------------------------------------

def audit_suppressions(files: Sequence[SourceFile],
                       result: AnalysisResult) -> List[Dict]:
    """Stale inline suppressions: a ``# paddle-lint: disable[-next|-file]``
    comment whose pass no longer fires at that site. The inline mirror
    of the baseline's shrink-only rule — fixing a suppressed finding
    forces deleting its annotation, so the suppression surface can only
    shrink. A suppression naming a pass that does not exist is flagged
    too (a typo'd annotation silences nothing and lies to the reader).
    Passes that did not run this invocation are skipped (cannot judge).
    """
    ran = set(result.passes_run)
    known = set(registered_passes())
    used_line = {(f.path, f.pass_name, f.line) for f in result.suppressed}
    used_file = {(f.path, f.pass_name) for f in result.suppressed}
    stale: List[Dict] = []
    for sf in files:
        for site in sf.suppress_sites:
            for name in site['names']:
                if name == 'all':
                    passes = sorted(ran)
                elif name not in known:
                    stale.append({'path': sf.rel,
                                  'line': site['comment_line'],
                                  'pass': name, 'kind': site['kind'],
                                  'reason': 'unknown-pass'})
                    continue
                elif name not in ran:
                    continue
                else:
                    passes = [name]
                if site['effective_line'] is None:
                    live = any((sf.rel, p) in used_file for p in passes)
                else:
                    live = any(
                        (sf.rel, p, site['effective_line']) in used_line
                        for p in passes)
                if not live:
                    stale.append({'path': sf.rel,
                                  'line': site['comment_line'],
                                  'pass': name, 'kind': site['kind'],
                                  'reason': 'no-finding'})
    return stale


def compute_stats(result: AnalysisResult,
                  stale_suppressions: Sequence[Dict],
                  baseline: Optional[Baseline] = None) -> Dict:
    """Per-pass finding/suppression/baseline accounting (the --stats
    payload). `clean` here is stricter than AnalysisResult.clean: stale
    suppressions fail the run the same way stale baseline entries do."""
    def _per_pass(findings: Iterable[Finding]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in findings:
            out[f.pass_name] = out.get(f.pass_name, 0) + 1
        return out

    baseline_per_pass: Dict[str, int] = {}
    for key in (baseline.entries if baseline else {}):
        p = key.split('::', 1)[0]
        baseline_per_pass[p] = baseline_per_pass.get(p, 0) + 1
    passes = {}
    for name in result.passes_run:
        passes[name] = {
            'findings': result.counts().get(name, 0),
            'grandfathered': _per_pass(result.grandfathered).get(name, 0),
            'suppressed': _per_pass(result.suppressed).get(name, 0),
            'baseline_entries': baseline_per_pass.get(name, 0),
            'stale_suppressions': sum(
                1 for s in stale_suppressions if s['pass'] == name),
        }
    return {
        'passes': passes,
        'files_scanned': result.files_scanned,
        'stale_suppressions': list(stale_suppressions),
        'stale_baseline': list(result.stale_baseline),
        'clean': result.clean and not stale_suppressions,
    }


def render_stats_text(stats: Dict) -> str:
    lines = ['pass                     findings  grandfathered  '
             'suppressed  baseline  stale-suppr']
    for name, row in sorted(stats['passes'].items()):
        lines.append(
            f'{name:<24} {row["findings"]:>8}  {row["grandfathered"]:>13}'
            f'  {row["suppressed"]:>10}  {row["baseline_entries"]:>8}'
            f'  {row["stale_suppressions"]:>11}')
    for s in stats['stale_suppressions']:
        why = ('names unknown pass' if s['reason'] == 'unknown-pass'
               else 'its pass no longer fires here')
        lines.append(
            f'STALE-SUPPRESSION: {s["path"]}:{s["line"]} '
            f'[{s["pass"]}] — {why}; delete the annotation '
            f'(shrink-only, same contract as the baseline)')
    for key in stats['stale_baseline']:
        lines.append(f'STALE-BASELINE: {key}')
    lines.append(
        f'paddle-lint --stats: {stats["files_scanned"]} files, '
        f'{len(stats["stale_suppressions"])} stale suppression(s), '
        f'{"CLEAN" if stats["clean"] else "NOT CLEAN"}')
    return '\n'.join(lines)


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------

def render_text(result: AnalysisResult) -> str:
    lines = []
    for f in sorted(result.findings, key=lambda f: (f.path, f.line, f.col)):
        lines.append(f.render())
    for key in result.stale_baseline:
        lines.append(f'STALE-BASELINE: {key} — the finding was fixed; '
                     f'delete its baseline entry (shrink-only)')
    counts = result.counts()
    summary = ', '.join(f'{k}={v}' for k, v in sorted(counts.items())) or 'clean'
    lines.append(
        f'paddle-lint: {len(result.findings)} finding(s) [{summary}], '
        f'{len(result.grandfathered)} grandfathered, '
        f'{len(result.suppressed)} suppressed, '
        f'{len(result.stale_baseline)} stale baseline entr(ies), '
        f'{result.files_scanned} files, '
        f'passes: {", ".join(result.passes_run)}')
    return '\n'.join(lines)


def render_json(result: AnalysisResult) -> str:
    return json.dumps({
        'findings': [f.as_dict() for f in sorted(
            result.findings, key=lambda f: (f.path, f.line, f.col))],
        'grandfathered': [f.as_dict() for f in result.grandfathered],
        'suppressed': [f.as_dict() for f in result.suppressed],
        'stale_baseline': list(result.stale_baseline),
        'summary': {
            'finding_count': len(result.findings),
            'per_pass': result.counts(),
            'grandfathered': len(result.grandfathered),
            'suppressed': len(result.suppressed),
            'stale_baseline': len(result.stale_baseline),
            'files_scanned': result.files_scanned,
            'passes_run': list(result.passes_run),
            'clean': result.clean,
        },
    }, indent=1)
