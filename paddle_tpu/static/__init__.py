"""paddle.static compatibility layer (upstream: python/paddle/static/ —
Program, program_guard, data, Executor).

TPU-native design: there is no separate graph IR. Ops recorded on the
DyGraph tape ARE the program — `static.data` creates named placeholder
Tensors, user code builds the graph eagerly under `program_guard`
(placeholders carry zero values at build time), and `Executor.run`
replays the recorded subgraph as one pure jax function of the feeds
(autograd._build_pure), jitted and cached per feed signature. XLA is
the program; the tape is the ProgramDesc.

Supported surface: enable_static/disable_static, in_static_mode, data,
Program, program_guard, default_main_program, default_startup_program,
Executor(place).run(feed=..., fetch_list=..., return_numpy=...),
global_scope (no-op shim), InputSpec (re-export). Static-graph TRAINING
(optimizer.minimize inside a program) is deliberately out: the
framework's training path is DyGraph + jit.TrainStep (see SCOPE.md).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec parity)
from ..tensor import Tensor


class _Mode(threading.local):
    def __init__(self):
        self.static = False


_mode = _Mode()


def enable_static(place=None):
    """`place` accepted for upstream signature parity (device selection
    is global via paddle.set_device here)."""
    _mode.static = True


def disable_static(place=None):
    _mode.static = False


def in_static_mode() -> bool:
    return _mode.static


class Program:
    """A named collection of placeholders + whatever tape the user built
    from them (upstream: framework.Program / ProgramDesc)."""

    def __init__(self):
        self.placeholders: Dict[str, Tensor] = {}
        self._jit_cache: Dict[Any, Any] = {}

    def clone(self, for_test: bool = False) -> 'Program':
        return self  # the tape is immutable once recorded

    # upstream parity helpers
    def all_parameters(self):
        return []


class _ProgramStack(threading.local):
    def __init__(self):
        self.main = Program()
        self.startup = Program()
        self.stack: List[Program] = []


_programs = _ProgramStack()


def default_main_program() -> Program:
    return _programs.stack[-1] if _programs.stack else _programs.main


def default_startup_program() -> Program:
    return _programs.startup


class program_guard:
    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _programs.stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _programs.stack.pop()
        return False


def data(name: str, shape, dtype='float32', lod_level=0) -> Tensor:
    """Declare a named feed placeholder (upstream: paddle.static.data).

    Unknown dims (None/-1) are built at extent 1; Executor.run replays
    the graph at the actual feed shapes, so ops must be
    batch-polymorphic (true for the op set: jnp broadcasting rules)."""
    build_shape = tuple(1 if (d is None or int(d) < 0) else int(d)
                        for d in shape)
    t = Tensor(jnp.zeros(build_shape, jnp.dtype(dtype)))
    # placeholders must be tape-recorded downstream (the tape IS the
    # program), and the tape skips ops whose inputs are all
    # stop_gradient — so feeds are marked differentiable at build time
    t.stop_gradient = False
    t.name = name
    prog = default_main_program()
    prog.placeholders[name] = t
    return t


def global_scope():
    """Scope shim: variables live on Tensors, not in a C++ scope."""
    return None


class Executor:
    """Runs a recorded program (upstream: paddle/fluid/executor.py; here
    a jitted replay of the tape — one XLA executable per feed
    signature)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        prog = program or default_main_program()
        feed = feed or {}
        if not fetch_list:
            raise ValueError('Executor.run needs a non-empty fetch_list')
        fetches = [f for f in fetch_list]
        for f in fetches:
            if not isinstance(f, Tensor):
                raise TypeError(
                    f'fetch_list entries must be Tensors built from '
                    f'static.data placeholders, got {type(f).__name__}')
        names = sorted(feed)
        unknown = [n for n in names if n not in prog.placeholders]
        if unknown:
            raise KeyError(
                f'feed names {unknown} were never declared via '
                f'static.data in this program '
                f'(declared: {sorted(prog.placeholders)})')
        inputs = [prog.placeholders[n] for n in names]
        vals = [jnp.asarray(feed[n]) for n in names]
        key = (tuple(names),
               tuple((v.shape, str(v.dtype)) for v in vals),
               tuple(id(f) for f in fetches))
        runner = prog._jit_cache.get(key)
        if runner is None:
            pure, _ = autograd._build_pure(fetches, inputs)

            def traced(*xvals):
                with autograd.functional_scope():
                    return pure(*xvals)
            runner = jax.jit(traced)
            prog._jit_cache[key] = runner
        outs = runner(*vals)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


class _StaticNN:
    """paddle.static.nn (upstream: python/paddle/static/nn/): the
    classic static-graph layer helpers. Here each call builds the same
    nn.Layer and applies it immediately — under program_guard the tape
    records it into the Program like any other op."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as _nn
        from ..tensor import Tensor
        import numpy as np
        v = x if isinstance(x, Tensor) else Tensor(x)
        in_dim = int(np.prod(v.shape[num_flatten_dims:]))
        if v.ndim > num_flatten_dims + 1:
            v = v.reshape(list(v.shape[:num_flatten_dims]) + [-1])
        layer = _nn.Linear(in_dim, size)
        out = layer(v)
        if activation:
            out = getattr(_nn.functional, activation)(out)
        return out

    @staticmethod
    def batch_norm(input, is_test=False, momentum=0.9, epsilon=1e-5,
                   data_layout='NCHW', name=None):
        from .. import nn as _nn
        ch = input.shape[1 if data_layout == 'NCHW' else -1]
        layer = _nn.BatchNorm(ch, momentum=momentum, epsilon=epsilon,
                              data_format=data_layout)
        if is_test:
            layer.eval()
        return layer(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               dilation=1, groups=1, act=None, name=None):
        from .. import nn as _nn
        layer = _nn.Conv2D(input.shape[1], num_filters, filter_size,
                           stride=stride, padding=padding,
                           dilation=dilation, groups=groups)
        out = layer(input)
        if act:
            out = getattr(_nn.functional, act)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,
                  name=None):
        from .. import nn as _nn
        layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx)
        return layer(input)


nn = _StaticNN()
