"""paddle.io — datasets, samplers, DataLoader (upstream:
python/paddle/io/).

TPU-native DataLoader design: decode happens on background Python
threads, but the per-sample copy/convert into the batch buffer runs on
the C++ decoder pool (csrc/staging.cpp) writing straight into a staging
ring-buffer slot — no numpy `stack` allocation per batch, no GIL during
the copies. The assembled contiguous slot is handed to the device while
workers fill the next slot (host→device overlap). When the native
runtime or a compiler is unavailable, everything falls back to plain
numpy collate with identical semantics.
"""
from __future__ import annotations

import itertools
import math
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from ..analysis.runtime import concurrency as _concurrency
from ..tensor import Tensor
from . import native


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError('IterableDataset is index-free; iterate it')

    def __len__(self):
        raise TypeError('IterableDataset has no length')


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                  for t in tensors]
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError('all tensors must share dim 0')
        self.arrays = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return self.arrays[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, i):
        return self.dataset[self.indices[i]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError('sum of lengths must equal dataset size')
    rng = np.random.RandomState(generator if isinstance(generator, int)
                                else None)
    perm = rng.permutation(total)
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


class ConcatDataset(Dataset):
    """End-to-end concatenation of map-style datasets (upstream
    paddle.io.ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError('ConcatDataset needs at least one dataset')
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __getitem__(self, i):
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(f'index {i - n if i < 0 else i} out of '
                             f'range for ConcatDataset of length {n}')
        import bisect
        di = bisect.bisect_right(self.cumulative_sizes, i)
        prev = self.cumulative_sizes[di - 1] if di else 0
        return self.datasets[di][i - prev]

    def __len__(self):
        return self.cumulative_sizes[-1]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError('datasets must share length')

    def __getitem__(self, i):
        out = []
        for d in self.datasets:
            s = d[i]
            out.extend(s if isinstance(s, (tuple, list)) else [s])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator
        self.epoch: Optional[int] = None  # set_epoch => deterministic order

    def set_epoch(self, epoch: int):
        """Seed this epoch's order from (generator seed, epoch) so the
        sequence is reproducible across interruption/resume (upstream:
        DistributedBatchSampler.set_epoch, extended to plain sampling)."""
        self.epoch = int(epoch)

    def _rng(self):
        base = self.generator if isinstance(self.generator, int) else 0
        if self.epoch is not None:
            return np.random.RandomState(
                (base * 1000003 + self.epoch) % (2 ** 31 - 1))
        return np.random.RandomState(
            self.generator if isinstance(self.generator, int) else None)

    def __iter__(self):
        n = len(self.data_source)
        rng = self._rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        if not replacement and num_samples > len(self.weights):
            raise ValueError('cannot draw more than population w/o '
                             'replacement')
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last \
            else math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """Each dp rank sees a disjoint shard (upstream:
    paddle.io.DistributedBatchSampler). On the single-controller TPU
    runtime the global batch is usually fed whole and sharded by
    `shard_batch`, but per-host sharding still needs this for multi-host
    input pipelines."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env
        self.num_replicas = num_replicas if num_replicas is not None \
            else env.get_world_size()
        self.rank = rank if rank is not None else env.get_rank()
        if self.rank >= self.num_replicas:
            raise ValueError('rank must be < num_replicas')
        self.dataset = dataset
        self.shuffle = shuffle
        self.epoch = 0
        n = len(dataset)
        self.num_samples = math.ceil(n / self.num_replicas)
        super().__init__(dataset=dataset, sampler=SequenceSampler(dataset),
                         batch_size=batch_size, drop_last=drop_last)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        order = (np.random.RandomState(self.epoch).permutation(n)
                 if self.shuffle else np.arange(n))
        total = self.num_samples * self.num_replicas
        padded = np.resize(order, total)  # wrap-around padding
        shard = padded[self.rank:total:self.num_replicas]
        batch = []
        for idx in shard.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)


# ---------------------------------------------------------------------------
# collate
# ---------------------------------------------------------------------------

def default_collate_fn(batch: List[Any]):
    first = batch[0]
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate_fn([b[i] for b in batch])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in first}
    if isinstance(first, Tensor):
        return Tensor(np.stack([b.numpy() for b in batch]))
    if isinstance(first, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(first, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(first, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    raise TypeError(f'cannot collate {type(first).__name__}')


def _flat_numeric_samples(sample) -> Optional[List[np.ndarray]]:
    """If a sample is a flat tuple/list of fixed-dtype ndarrays, return
    them (the native fast path); else None."""
    items = sample if isinstance(sample, (tuple, list)) else (sample,)
    out = []
    for it in items:
        if isinstance(it, np.ndarray) and it.dtype != object:
            out.append(np.ascontiguousarray(it))
        elif isinstance(it, (int, np.integer)):
            out.append(np.asarray(it, np.int64))
        elif isinstance(it, (float, np.floating)):
            out.append(np.asarray(it, np.float32))
        else:
            return None
    return out


class _NativeCollator:
    """Assemble batches in a staging slot via the C++ decoder pool."""

    def __init__(self, n_threads: int, slot_bytes: int, n_slots: int = 4):
        self.pool = native.DecoderPool(max(1, n_threads))
        self.staging = native.StagingBuffer(slot_bytes, n_slots)

    def collate(self, samples: List[List[np.ndarray]], structure):
        nfields = len(samples[0])
        bsz = len(samples)
        # field layout inside the slot, 64-byte aligned
        offsets, sizes, metas = [], [], []
        ofs = 0
        for f in range(nfields):
            per = samples[0][f]
            nbytes = per.nbytes * bsz
            offsets.append(ofs)
            sizes.append(per.nbytes)
            metas.append(((bsz,) + per.shape, per.dtype))
            ofs += (nbytes + 63) & ~63
        if ofs > self.staging.slot_bytes:
            return None  # batch too large for slots; caller falls back
        slot = self.staging.acquire()
        if slot < 0:
            return None
        # try/finally: an exception between acquire() and release() would
        # otherwise leak the slot permanently — after n_slots leaks every
        # worker blocks forever inside staging_acquire with no watchdog.
        try:
            ticket = self.pool.ticket()
        except Exception:
            self.staging.release(slot)
            raise
        njobs = 0
        try:
            keepalive = []
            for f in range(nfields):
                base = self.staging.addr(slot, offsets[f])
                for b, s in enumerate(samples):
                    arr = s[f]
                    keepalive.append(arr)
                    self.pool.submit_memcpy(
                        arr.ctypes.data, base + b * sizes[f], arr.nbytes,
                        ticket)
                    njobs += 1
            self.pool.wait(ticket, njobs)
            out = []
            for f in range(nfields):
                shape, dtype = metas[f]
                view = self.staging.view(
                    slot, nbytes=int(np.prod(shape)) * dtype.itemsize,
                    dtype=dtype, shape=shape, offset=offsets[f])
                out.append(Tensor(np.array(view)))  # device put copies
        finally:
            # drain jobs already submitted BEFORE freeing the ticket or
            # releasing the slot — C++ workers still hold pointers to both
            # (freeing early would be a heap use-after-free / slot race)
            self.pool.wait(ticket, njobs)
            self.pool.ticket_free(ticket)
            self.staging.release(slot)
        if structure == 'single':
            return out[0]
        return tuple(out)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._custom_collate = collate_fn is not None
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(2, int(prefetch_factor))
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
        self._native: Optional[_NativeCollator] = None
        if (self.num_workers > 0 and not self._custom_collate
                and native.available()):
            try:
                self._native = _NativeCollator(
                    self.num_workers, slot_bytes=64 << 20)
            except Exception:
                # native collator unavailable: python collation is the
                # supported fallback, but count it — a fleet silently
                # running the slow path is a perf bug, not a preference
                from ..observability import count_suppressed
                count_suppressed('io.native_collator')
                self._native = None
        # mid-epoch resume cursor (SURVEY §5 "dataloader epoch/seed
        # state"): epochs are deterministically seeded via set_epoch, so
        # {epoch, batch_idx} fully determines the remaining sequence
        self._epoch = 0
        self._batch_idx = 0
        self._pending_skip = 0
        self._in_progress = False  # a pass started but never completed
        self._pushed_epoch: Optional[int] = None  # last epoch we seeded
        self._iter_gen = 0  # only the newest iterator drives the cursor

    def __len__(self):
        if self._iterable:
            raise TypeError('DataLoader over IterableDataset has no len')
        return len(self.batch_sampler)

    # -- mid-epoch resume ---------------------------------------------------
    def set_epoch(self, epoch: int):
        """Fix this epoch's shuffle order (forwarded to the sampler).
        Called automatically at the start of each iteration with the
        tracked epoch counter, so shuffle order is reproducible by
        default — the property mid-epoch resume rests on."""
        self._epoch = int(epoch)
        bs = self.batch_sampler
        if bs is None:
            return
        if hasattr(bs, 'set_epoch'):
            bs.set_epoch(self._epoch)
        elif getattr(bs, 'sampler', None) is not None \
                and hasattr(bs.sampler, 'set_epoch'):
            bs.sampler.set_epoch(self._epoch)

    def state_dict(self) -> dict:
        """Cursor {epoch, batch_idx}: how many batches of which epoch
        have been consumed (upstream: fleet dataset/reader state)."""
        return {'epoch': self._epoch, 'batch_idx': self._batch_idx}

    def set_state_dict(self, state: dict):
        """Resume mid-epoch: the next iteration replays epoch `epoch`'s
        deterministic order and skips the first `batch_idx` batches."""
        self._epoch = int(state['epoch'])
        self._batch_idx = int(state['batch_idx'])
        self._pending_skip = self._batch_idx
        self._in_progress = False

    # -- iteration ----------------------------------------------------------
    def _index_batches(self) -> Iterator[List[int]]:
        it = iter(self.batch_sampler)
        for _ in range(self._pending_skip):
            next(it, None)
        self._pending_skip = 0
        yield from it

    def _fetch(self, indices: List[int]):
        return [self.dataset[i] for i in indices]

    def _collate(self, raw: List[Any]):
        if self._native is not None:
            flat = [_flat_numeric_samples(s) for s in raw]
            if all(f is not None for f in flat) and flat:
                shapes0 = [(a.shape, a.dtype) for a in flat[0]]
                if all([(a.shape, a.dtype) for a in f] == shapes0
                       for f in flat):
                    structure = ('single'
                                 if not isinstance(raw[0], (tuple, list))
                                 else 'tuple')
                    out = self._native.collate(flat, structure)
                    if out is not None:
                        return out
        return self.collate_fn(raw)

    def _iter_sync(self):
        if self._iterable:
            skip, self._pending_skip = self._pending_skip, 0
            emitted = 0
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    emitted += 1
                    if emitted > skip:
                        yield self._collate(batch)
                    batch = []
            if batch and not self.drop_last:
                emitted += 1
                if emitted > skip:
                    yield self._collate(batch)
            return
        for idx in self._index_batches():
            yield self._collate(self._fetch(idx))

    def _iter_workers(self):
        """Thread team: fetch+decode in parallel, preserve batch order.
        Backpressure: workers stall once `cap` collated batches are
        waiting, so prefetch depth (not dataset size) bounds host memory."""
        cap = self.num_workers * self.prefetch_factor
        n_batches = max(0, len(self.batch_sampler) - self._pending_skip)
        index_it = enumerate(self._index_batches())
        lock = _concurrency.Lock('DataLoader.index_lock')
        stop = threading.Event()
        results: dict = {}
        results_cv = _concurrency.Condition(
            name='DataLoader.results_cv')
        # bound in-flight batches with a semaphore acquired BEFORE taking
        # an index (never block the insert — blocking the worker that
        # holds the batch the consumer is waiting on would deadlock)
        inflight = threading.Semaphore(cap)

        def worker():
            while not stop.is_set():
                inflight.acquire()
                if stop.is_set():
                    return
                with lock:
                    try:
                        seq, idx = next(index_it)
                    except StopIteration:
                        inflight.release()
                        return
                try:
                    batch = self._collate(self._fetch(idx))
                    err = None
                except Exception as e:  # surface in consumer
                    batch, err = None, e
                with results_cv:
                    results[seq] = (batch, err)
                    results_cv.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for want in range(n_batches):
                with results_cv:
                    while want not in results:
                        results_cv.wait(timeout=0.1)
                        if not any(t.is_alive() for t in threads) \
                                and want not in results:
                            raise RuntimeError('DataLoader workers died')
                    batch, err = results.pop(want)
                inflight.release()
                if err is not None:
                    raise err
                yield batch
        finally:
            stop.set()
            for _ in threads:  # unblock workers parked on the semaphore
                inflight.release()

    def _sampler_epoch(self) -> Optional[int]:
        bs = self.batch_sampler
        if bs is None:
            return None
        if getattr(bs, 'epoch', None) is not None:
            return bs.epoch
        return getattr(getattr(bs, 'sampler', None), 'epoch', None)

    def __iter__(self):
        # honor the classic sampler.set_epoch resume idiom: if the user
        # set an epoch on the (batch) sampler directly since we last
        # seeded it, adopt it instead of clobbering with our counter
        ext = self._sampler_epoch()
        if ext is not None and ext != self._pushed_epoch:
            self._epoch = int(ext)
        if self._pending_skip == 0:
            if self._in_progress:
                # a previous pass was abandoned early (break / exception):
                # move on so re-iterating gets a FRESH shuffle order, not
                # a silent replay of the same leading batches
                self._epoch += 1
                self._in_progress = False
            self._batch_idx = 0  # fresh (non-resume) pass restarts cursor
        self.set_epoch(self._epoch)  # pin this epoch's shuffle order
        self._pushed_epoch = self._epoch
        if self.num_workers > 0 and not self._iterable:
            inner = self._iter_workers()
        else:
            inner = self._iter_sync()
        self._iter_gen += 1
        return self._track(inner, self._iter_gen)

    def _track(self, inner, gen):
        """Advance the resume cursor as batches are consumed; roll the
        epoch when an iteration runs to completion. Only the newest
        iterator moves the cursor — a stale concurrent iterator keeps
        yielding but cannot corrupt resume state."""
        for batch in inner:
            if gen == self._iter_gen:
                self._in_progress = True
                self._batch_idx += 1
            yield batch
        if gen == self._iter_gen:
            self._epoch += 1
            self._batch_idx = 0
            self._in_progress = False


def get_worker_info():
    return None  # thread-based workers share the dataset object


__all__ = [
    'ConcatDataset',
    'BatchSampler', 'ChainDataset', 'ComposeDataset', 'DataLoader',
    'Dataset', 'DistributedBatchSampler', 'IterableDataset',
    'RandomSampler', 'Sampler', 'SequenceSampler', 'Subset',
    'TensorDataset', 'WeightedRandomSampler', 'default_collate_fn',
    'get_worker_info', 'random_split',
]


class SubsetRandomSampler(Sampler):
    """Sample the given indices in random order (upstream:
    python/paddle/io/sampler.py:SubsetRandomSampler)."""

    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = np.random.RandomState(
            self.generator if isinstance(self.generator, int) else None)
        return iter([self.indices[i]
                     for i in rng.permutation(len(self.indices))])

    def __len__(self):
        return len(self.indices)
