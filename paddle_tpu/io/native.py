"""ctypes bindings for the C++ staging runtime (csrc/staging.cpp).

Builds the shared library on first use (g++ -O3 -shared), caches it under
csrc/build/, and degrades gracefully: `available()` returns False when no
compiler is present and the DataLoader falls back to pure-python collate.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from ..analysis.runtime import concurrency as _concurrency

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), 'csrc')
_BUILD = os.path.join(_CSRC, 'build')
_LIB_PATH = os.path.join(_BUILD, 'libpaddle_tpu_staging.so')

_lock = _concurrency.Lock('native._lock')
_lib = None
_tried = False

JOB_MEMCPY = 0
JOB_U8_TO_F32 = 1
JOB_F32_SCALE = 2


def _build():
    src = os.path.join(_CSRC, 'staging.cpp')
    os.makedirs(_BUILD, exist_ok=True)
    tmp = _LIB_PATH + '.tmp.so'
    subprocess.run(
        ['g++', '-O3', '-fPIC', '-shared', '-std=c++17', '-pthread',
         src, '-o', tmp],
        check=True, capture_output=True)
    os.replace(tmp, _LIB_PATH)


def _bind(lib):
    lib.staging_create.restype = ctypes.c_void_p
    lib.staging_create.argtypes = [ctypes.c_size_t, ctypes.c_int]
    lib.staging_acquire.restype = ctypes.c_int
    lib.staging_acquire.argtypes = [ctypes.c_void_p]
    lib.staging_ptr.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.staging_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.staging_slot_bytes.restype = ctypes.c_size_t
    lib.staging_slot_bytes.argtypes = [ctypes.c_void_p]
    lib.staging_commit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_size_t]
    lib.staging_pop.restype = ctypes.c_int
    lib.staging_pop.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_size_t)]
    lib.staging_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.staging_close.argtypes = [ctypes.c_void_p]
    lib.staging_destroy.argtypes = [ctypes.c_void_p]
    lib.pool_create.restype = ctypes.c_void_p
    lib.pool_create.argtypes = [ctypes.c_int]
    lib.pool_submit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_size_t, ctypes.c_float,
                                ctypes.c_float, ctypes.c_void_p]
    lib.pool_ticket_create.restype = ctypes.c_void_p
    lib.pool_ticket_count.restype = ctypes.c_int
    lib.pool_ticket_count.argtypes = [ctypes.c_void_p]
    lib.pool_ticket_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pool_ticket_destroy.argtypes = [ctypes.c_void_p]
    lib.pool_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _stale() -> bool:
    """The .so must be rebuilt when staging.cpp is newer (a stale binary
    loaded over a changed ABI via ctypes corrupts memory silently)."""
    src = os.path.join(_CSRC, 'staging.cpp')
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        return os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def get_lib():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _stale():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception:  # paddle-lint: disable=swallowed-exception -- optional native lib gate; absence is a supported config surfaced via available()
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


class StagingBuffer:
    """Ring of fixed-size aligned host slots (consumer side returns numpy
    views onto slot memory — zero copies between collate and device put)."""

    def __init__(self, slot_bytes: int, n_slots: int = 4):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError('native staging runtime unavailable')
        self._h = self._lib.staging_create(slot_bytes, n_slots)
        if not self._h:
            raise MemoryError('staging_create failed')
        self.slot_bytes = slot_bytes

    def acquire(self) -> int:
        return self._lib.staging_acquire(self._h)

    def view(self, slot: int, nbytes=None, dtype=np.uint8, shape=None,
             offset=0):
        ptr = self._lib.staging_ptr(self._h, slot)
        n = nbytes if nbytes is not None else self.slot_bytes - offset
        buf = (ctypes.c_uint8 * n).from_address(
            ctypes.addressof(ptr.contents) + offset)
        arr = np.frombuffer(buf, dtype=dtype)
        return arr.reshape(shape) if shape is not None else arr

    def addr(self, slot: int, offset: int = 0) -> int:
        ptr = self._lib.staging_ptr(self._h, slot)
        return ctypes.addressof(ptr.contents) + offset

    def commit(self, slot: int, nbytes: int):
        self._lib.staging_commit(self._h, slot, nbytes)

    def pop(self):
        n = ctypes.c_size_t(0)
        idx = self._lib.staging_pop(self._h, ctypes.byref(n))
        return idx, n.value

    def release(self, slot: int):
        self._lib.staging_release(self._h, slot)

    def close(self):
        if self._h:
            self._lib.staging_close(self._h)

    def __del__(self):
        try:
            if self._h:
                self._lib.staging_destroy(self._h)
                self._h = None
        except Exception:  # paddle-lint: disable=swallowed-exception -- destructor path: interpreter/library may already be tearing down
            pass


class DecoderPool:
    """C++ worker team for GIL-free sample decode/copy jobs."""

    def __init__(self, n_threads: int):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError('native staging runtime unavailable')
        self._h = self._lib.pool_create(n_threads)

    def ticket(self):
        return self._lib.pool_ticket_create()

    def submit_memcpy(self, src_addr: int, dst_addr: int, nbytes: int,
                      ticket):
        self._lib.pool_submit(self._h, JOB_MEMCPY, src_addr, dst_addr,
                              nbytes, 1.0, 0.0, ticket)

    def submit_u8_to_f32(self, src_addr: int, dst_addr: int, n: int,
                         scale: float, shift: float, ticket):
        self._lib.pool_submit(self._h, JOB_U8_TO_F32, src_addr, dst_addr,
                              n, scale, shift, ticket)

    def wait(self, ticket, count: int):
        self._lib.pool_ticket_wait(ticket, count)

    def ticket_done(self, ticket) -> int:
        return self._lib.pool_ticket_count(ticket)

    def ticket_free(self, ticket):
        self._lib.pool_ticket_destroy(ticket)

    def __del__(self):
        try:
            if self._h:
                self._lib.pool_destroy(self._h)
                self._h = None
        except Exception:  # paddle-lint: disable=swallowed-exception -- destructor path: interpreter/library may already be tearing down
            pass
