"""paddle.quantization compatibility layer (upstream:
python/paddle/quantization/ — QuantConfig, PTQ, QAT, observers/quanters).

TPU-native design, two paths:
- PTQ (post-training): per-channel absmax int8 weight quantization of
  Linear layers. The quantized layer stores int8 weights + fp32 scales
  and dequantizes into the matmul dtype at call time — weights sit in
  HBM at 1/2 (vs bf16) or 1/4 (vs fp32) the bytes, and the matmul stays
  on the MXU's native bf16 path.
- QAT (quant-aware training): FakeQuantAbsMax straight-through-estimator
  wrapping on Linear forward — quantization error is simulated in fwd,
  gradients pass through unchanged (lax.stop_gradient residual trick).
"""
from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.common_layers import Linear
from ..nn.layer import Layer
from ..tensor import Tensor, apply_op

from . import observers as observers  # noqa: F401  (paddle.quantization.observers)
from .observers import (AbsmaxChannelObserver, AbsmaxObserver, AVGObserver,
                        BaseObserver, EMAObserver, HistObserver, KLObserver,
                        MSEObserver)

__all__ = ['QuantConfig', 'PTQ', 'QAT', 'QuantedLinear',
           'FakeQuantAbsMax', 'quanted_state_bytes', 'observers',
           'AbsmaxObserver', 'AbsmaxChannelObserver', 'AVGObserver',
           'EMAObserver', 'HistObserver', 'KLObserver', 'MSEObserver',
           'kv_page_scales', 'kv_quantize_page', 'kv_dequantize_page']

# int8 KV-cache quantization (ISSUE 16): traced per-(page, head) absmax
# helpers shared by the paged KV pool's scatter/gather, the fused
# paged-attention kernel's dequant, and — for parity — the host-side
# AbsmaxChannelObserver (same absmax/127 semantics, observers.py).
_KV_QMAX = 127.0


def kv_page_scales(page, qmax: float = _KV_QMAX):
    """Per-(page, head) absmax int8 scale for KV page slabs shaped
    [..., page_size, H, D]: reduce |x| over the rows and head_dim, keep
    the head axis — one scale per head per page, so a page's scale never
    couples heads with very different activation ranges. Zero pages get
    scale 1.0 (quantize to zero, never divide by zero). Traced."""
    amax = jnp.max(jnp.abs(page.astype(jnp.float32)), axis=(-3, -1))
    return jnp.where(amax > 0, amax / qmax, 1.0)


def kv_quantize_page(page, scales, qmax: float = _KV_QMAX):
    """Round-clip `page` [..., ps, H, D] to int8 at per-(page, head)
    `scales` [..., H]. Traced (lives inside scatter_pages)."""
    q = jnp.round(page.astype(jnp.float32) / scales[..., None, :, None])
    return jnp.clip(q, -qmax, qmax).astype(jnp.int8)


def kv_dequantize_page(q, scales, dtype):
    """Inverse of kv_quantize_page: int8 pages [..., ps, H, D] back to
    `dtype` at per-(page, head) scales [..., H]. Traced (lives inside
    gather_pages and the paged-attention kernels)."""
    return (q.astype(jnp.float32)
            * scales[..., None, :, None]).astype(dtype)

_OBSERVERS = {'abs_max': AbsmaxObserver, 'avg': AVGObserver,
              'ema': EMAObserver, 'hist': HistObserver,
              'kl': KLObserver, 'mse': MSEObserver}


class QuantConfig:
    """Which layers to quantize (upstream: paddle.quantization.QuantConfig
    with activation/weight quanter factories).

    activation: None (weight-only) or an observer — a name from
    {'abs_max','avg','ema','hist','kl','mse'}, an observer class, or a
    zero-arg factory. With an activation observer, PTQ.quantize inserts
    calibration observers; after running calibration batches,
    PTQ.convert bakes each observed scale into the deployed layer."""

    def __init__(self, activation=None, weight='abs_max_channel_wise'):
        self.activation = activation
        self.weight = weight
        self._types = (Linear,)

    def make_observer(self):
        a = self.activation
        if a is None:
            return None
        if isinstance(a, str):
            if a not in _OBSERVERS:
                raise ValueError(
                    f'unknown activation observer {a!r}; '
                    f'choose from {sorted(_OBSERVERS)}')
            return _OBSERVERS[a]()
        if isinstance(a, BaseObserver):
            # pre-built instance (e.g. HistObserver(percent=...)) is a
            # per-layer prototype: each quantized layer needs its OWN
            # calibration state, not a shared histogram
            return copy.deepcopy(a)
        return a() if callable(a) else a

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        self._types = tuple(set(self._types) | set(layer_types))
        return self


def _absmax_scales(w: np.ndarray, axis: int = 0) -> np.ndarray:
    """Per-output-channel absmax scale mapping to int8 [-127, 127]."""
    amax = np.max(np.abs(w), axis=axis, keepdims=True)
    return np.where(amax == 0, 1.0, amax / 127.0).astype(np.float32)


class QuantedLinear(Layer):
    """Linear with int8 weights + per-channel scales (upstream analogue:
    quanted nn.Linear produced by PTQ.convert)."""

    def __init__(self, in_features: int, out_features: int,
                 has_bias: bool = True, compute_dtype='float32'):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.compute_dtype = compute_dtype
        self.register_buffer('weight_int8', Tensor(
            jnp.zeros((in_features, out_features), jnp.int8)))
        self.register_buffer('weight_scale', Tensor(
            jnp.ones((1, out_features), jnp.float32)))
        self.bias = None
        self.act_scale: Optional[float] = None  # calibrated per-tensor

    @classmethod
    def from_linear(cls, lin: Linear,
                    act_scale: Optional[float] = None) -> 'QuantedLinear':
        w = np.asarray(lin.weight.value, np.float32)
        q = cls(w.shape[0], w.shape[1], has_bias=lin.bias is not None)
        scales = _absmax_scales(w)
        wq = np.clip(np.round(w / scales), -127, 127).astype(np.int8)
        q.weight_int8 = Tensor(jnp.asarray(wq))
        q.weight_scale = Tensor(jnp.asarray(scales))
        if lin.bias is not None:
            q.bias = lin.bias
        q.compute_dtype = ('bfloat16'
                           if lin.weight.value.dtype == jnp.bfloat16
                           else 'float32')
        q.act_scale = act_scale
        return q

    def forward(self, x):
        cd = jnp.dtype(self.compute_dtype)
        act_scale = self.act_scale

        def run(xv, wq, sc, *maybe_bias):
            if act_scale is not None:
                # deployed activation quantization: scale-round-clip at
                # the calibrated per-tensor scale (fused by XLA into the
                # surrounding elementwise ops)
                xv = jnp.clip(jnp.round(xv / act_scale), -127, 127) \
                    * jnp.asarray(act_scale, xv.dtype)
            w = wq.astype(cd) * sc.astype(cd)
            y = xv.astype(cd) @ w
            if maybe_bias:
                y = y + maybe_bias[0].astype(y.dtype)
            return y
        args = (x, self.weight_int8, self.weight_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply_op(run, *args, _name='quanted_linear')


class FakeQuantAbsMax(Layer):
    """QAT fake-quantizer: int8-rounds in forward, identity in backward
    (straight-through estimator via the stop_gradient residual)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.qmax = float(2 ** (quant_bits - 1) - 1)

    def forward(self, x):
        def fq(v):
            amax = jnp.max(jnp.abs(v), axis=0, keepdims=True)
            scale = jnp.where(amax == 0, 1.0, amax / self.qmax)
            q = jnp.clip(jnp.round(v / scale), -self.qmax, self.qmax) * scale
            # STE: forward sees q, backward sees identity
            return v + jax.lax.stop_gradient(q - v)
        return apply_op(fq, x, _name='fake_quant_absmax')


class _QATLinear(Layer):
    def __init__(self, lin: Linear, quanter: FakeQuantAbsMax):
        super().__init__()
        self.inner = lin
        self.quanter = quanter

    def forward(self, x):
        w = self.quanter(self.inner.weight)
        y = x @ w if self.inner.bias is None else x @ w + self.inner.bias
        return y


def _replace_layers(model: Layer, predicate, factory) -> int:
    n = 0
    for holder in model.sublayers(include_self=True):
        for name, child in list(holder.named_children()):
            if predicate(child):
                holder.add_sublayer(name, factory(child))
                n += 1
    return n


class _ObservedLinear(Layer):
    """Calibration-time wrapper: records activation stats, then runs the
    ORIGINAL float layer (observe-then-quantize, upstream PTQ flow)."""

    def __init__(self, lin: Linear, observer):
        super().__init__()
        self.inner = lin
        self.observer = observer

    def forward(self, x):
        self.observer(x)
        return self.inner(x)


class PTQ:
    """Post-training quantization driver (upstream:
    paddle.quantization.PTQ.quantize/convert).

    Weight-only (config.activation=None): quantize() returns the
    deployable int8-weight model directly. With an activation observer:
    quantize() inserts observers; run calibration batches through the
    returned model, then convert() bakes the observed scales into
    QuantedLinear's runtime activation fake-quant."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        cfg = self.config
        if cfg.activation is not None:
            factory = (lambda lin:
                       _ObservedLinear(lin, cfg.make_observer()))
        else:
            factory = QuantedLinear.from_linear
        if type(model) in cfg._types and isinstance(model, Linear):
            # the model IS the quantizable layer — no parent to rebind
            if inplace:
                raise ValueError('cannot quantize a bare Linear inplace; '
                                 'use the returned layer')
            return factory(model)
        m = model if inplace else copy.deepcopy(model)
        hits = _replace_layers(
            m, lambda l: type(l) in cfg._types and isinstance(l, Linear),
            factory)
        if hits == 0:
            raise ValueError('PTQ.quantize found no quantizable layers '
                             f'(config types: {cfg._types})')
        return m

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace calibration observers with deployed quantized layers
        (identity for weight-only PTQ, which deploys at quantize())."""
        if isinstance(model, _ObservedLinear):
            return QuantedLinear.from_linear(model.inner,
                                             model.observer.scales())
        m = model if inplace else copy.deepcopy(model)
        _replace_layers(
            m, lambda l: isinstance(l, _ObservedLinear),
            lambda o: QuantedLinear.from_linear(o.inner,
                                                o.observer.scales()))
        return m


class QAT:
    """Quant-aware training driver: wraps Linear weights in fake-quant
    STE nodes; `convert` turns the trained model into QuantedLinear."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if type(model) in self.config._types and isinstance(model, Linear):
            if inplace:
                raise ValueError('cannot quantize a bare Linear inplace; '
                                 'use the returned layer')
            return _QATLinear(copy.deepcopy(model), FakeQuantAbsMax())
        m = model if inplace else copy.deepcopy(model)
        hits = _replace_layers(
            m, lambda l: type(l) in self.config._types
            and isinstance(l, Linear),
            lambda lin: _QATLinear(lin, FakeQuantAbsMax()))
        if hits == 0:
            raise ValueError('QAT.quantize found no quantizable layers')
        return m

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        m = model if inplace else copy.deepcopy(model)
        _replace_layers(m, lambda l: isinstance(l, _QATLinear),
                        lambda q: QuantedLinear.from_linear(q.inner))
        return m


def quanted_state_bytes(model: Layer) -> int:
    """HBM bytes of quantized weight state (for compression reporting)."""
    total = 0
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, QuantedLinear):
            total += layer.weight_int8.value.nbytes
            total += layer.weight_scale.value.nbytes
    return total
