"""Activation observers + calibration (upstream:
python/paddle/quantization/observers/ — AbsmaxObserver, AVGObserver,
HistObserver, KLObserver, MSEObserver).

TPU-native notes: observers run during eager calibration passes (small
data, host-side stats are fine); the *deployed* artifact is a per-tensor
fp32 activation scale baked into QuantedLinear, whose runtime fake-quant
is a fused scale-round-clip-scale that XLA folds into the surrounding
elementwise work — the matmul itself stays on the MXU bf16 path.
"""
from __future__ import annotations

import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ['BaseObserver', 'AbsmaxObserver', 'AbsmaxChannelObserver',
           'AVGObserver', 'HistObserver', 'KLObserver', 'MSEObserver',
           'EMAObserver']

_QMAX = 127.0


class BaseObserver(Layer):
    """Records activation statistics during calibration; `scales()`
    yields the per-tensor quantization scale (absmax / 127 semantics)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.qmax = float(2 ** (quant_bits - 1) - 1)
        self._seen = False

    def forward(self, x):
        self._observe(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x, np.float32))
        self._seen = True
        return x

    def _observe(self, a: np.ndarray):
        raise NotImplementedError

    def _absmax(self) -> float:
        raise NotImplementedError

    def scales(self) -> float:
        if not self._seen:
            raise RuntimeError(
                f'{type(self).__name__} has seen no calibration data')
        amax = float(self._absmax())
        return amax / self.qmax if amax > 0 else 1.0


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (upstream observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._max = 0.0

    def _observe(self, a):
        self._max = max(self._max, float(np.max(np.abs(a))))

    def _absmax(self):
        return self._max


class AbsmaxChannelObserver(BaseObserver):
    """Per-CHANNEL running absmax (upstream analogue:
    abs_max_channel_wise weight semantics, applied to activations):
    tracks max |x| over every axis EXCEPT `channel_axis`, and `scales()`
    returns an ARRAY of per-channel scales instead of a float.

    This is the observer path behind the paged KV cache's per-(page,
    head) int8 scales: observing a [page_size, H, D] page slab with
    channel_axis=1 yields exactly the per-head scales the traced
    `quantization.kv_page_scales` computes inside scatter_pages — the
    parity test in the paged-KV suite holds the two to agreement."""

    def __init__(self, quant_bits: int = 8, channel_axis: int = -1):
        super().__init__(quant_bits)
        self.channel_axis = int(channel_axis)
        self._max = None

    def _observe(self, a):
        ax = self.channel_axis % a.ndim
        reduce_axes = tuple(i for i in range(a.ndim) if i != ax)
        m = np.max(np.abs(a), axis=reduce_axes)
        self._max = m if self._max is None else np.maximum(self._max, m)

    def _absmax(self):
        return self._max

    def scales(self) -> np.ndarray:
        if not self._seen:
            raise RuntimeError(
                f'{type(self).__name__} has seen no calibration data')
        amax = np.asarray(self._absmax(), np.float32)
        return np.where(amax > 0, amax / self.qmax,
                        1.0).astype(np.float32)


class AVGObserver(BaseObserver):
    """Mean of per-batch absmax (upstream observers/avg.py) — robust to
    a single outlier batch."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._sum = 0.0
        self._n = 0

    def _observe(self, a):
        self._sum += float(np.max(np.abs(a)))
        self._n += 1

    def _absmax(self):
        return self._sum / max(self._n, 1)


class EMAObserver(BaseObserver):
    """Exponential moving average of per-batch absmax."""

    def __init__(self, quant_bits: int = 8, momentum: float = 0.9):
        super().__init__(quant_bits)
        self.momentum = momentum
        self._ema = None

    def _observe(self, a):
        m = float(np.max(np.abs(a)))
        self._ema = m if self._ema is None \
            else self.momentum * self._ema + (1 - self.momentum) * m
    def _absmax(self):
        return self._ema


class _HistogramMixin(BaseObserver):
    """Shared |x| histogram with growable range (rebinning on overflow)."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048):
        super().__init__(quant_bits)
        self.bins = bins
        self._hist = np.zeros(bins, np.float64)
        self._range = 0.0

    def _observe(self, a):
        amax = float(np.max(np.abs(a)))
        if amax == 0.0:
            return
        if amax > self._range:
            new_range = amax * 1.25
            if self._range > 0:
                # rebin old counts into the wider range
                old_edges = np.linspace(0, self._range, self.bins + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                idx = np.minimum(
                    (centers / new_range * self.bins).astype(int),
                    self.bins - 1)
                nh = np.zeros(self.bins, np.float64)
                np.add.at(nh, idx, self._hist)
                self._hist = nh
            self._range = new_range
        h, _ = np.histogram(np.abs(a), bins=self.bins,
                            range=(0.0, self._range))
        self._hist += h


class HistObserver(_HistogramMixin):
    """Percentile-of-histogram scale (upstream observers/hist.py)."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.9999):
        super().__init__(quant_bits, bins)
        self.percent = percent

    def _absmax(self):
        c = np.cumsum(self._hist)
        if c[-1] == 0:
            return 0.0
        k = int(np.searchsorted(c, self.percent * c[-1]))
        return (k + 1) / self.bins * self._range


class KLObserver(_HistogramMixin):
    """TensorRT-style KL-divergence threshold search (upstream
    observers/kl.py): pick the clip point whose quantized distribution
    is closest (min KL) to the observed one."""

    def _absmax(self):
        hist = self._hist
        total = hist.sum()
        if total == 0:
            return 0.0
        nlevels = int(self.qmax) + 1  # 128 magnitude levels
        best_kl, best_i = np.inf, self.bins
        start = max(nlevels, self.bins // 16)
        for i in range(start, self.bins + 1, max(1, self.bins // 256)):
            # reference P: first i bins with the clipped tail dumped into
            # the last bin; candidate Q: the UN-dumped first i bins
            # quantized to nlevels and expanded — Q lacking the outlier
            # mass is exactly what penalizes aggressive clipping
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()
            if p.sum() == 0:
                continue
            raw = hist[:i]
            idx = (np.arange(i) * nlevels // i)
            counts = np.bincount(
                idx, weights=(raw > 0).astype(np.float64),
                minlength=nlevels)
            sums = np.bincount(idx, weights=raw, minlength=nlevels)
            # spread each level's mass evenly over its nonzero bins
            q = np.where(raw > 0,
                         sums[idx] / np.maximum(counts[idx], 1), 0.0)
            pn = p / p.sum()
            qs = q.sum()
            if qs == 0:
                continue
            qn = q / qs
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return best_i / self.bins * self._range


class MSEObserver(_HistogramMixin):
    """Scale minimizing quantization MSE over the observed histogram
    (upstream observers/mse.py): grid-search clip thresholds, score by
    sum(hist * (bin_center - dequant(quant(bin_center)))^2)."""

    def _absmax(self):
        if self._hist.sum() == 0:
            return 0.0
        edges = np.linspace(0, self._range, self.bins + 1)
        centers = (edges[:-1] + edges[1:]) / 2
        best_mse, best_t = np.inf, self._range
        for frac in np.linspace(0.2, 1.0, 40):
            t = frac * self._range
            scale = t / self.qmax
            q = np.clip(np.round(centers / scale), -self.qmax,
                        self.qmax) * scale
            mse = float(np.sum(self._hist * (centers - q) ** 2))
            if mse < best_mse:
                best_mse, best_t = mse, t
        return best_t
