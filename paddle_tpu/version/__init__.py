"""paddle.version (upstream: generated python/paddle/version/__init__.py)."""
full_version = '0.1.0'
major = '0'
minor = '1'
patch = '0'
rc = '0'
commit = 'unknown'
istaged = False
with_pip = False
cuda_version = 'False'   # TPU-native build: no CUDA
cudnn_version = 'False'
xpu_version = 'False'


def show():
    print(f'full_version: {full_version}')
    print(f'commit: {commit}')
    print('cuda: False (TPU-native build; device backend is PjRt/XLA)')


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
