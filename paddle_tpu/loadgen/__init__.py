"""paddle_tpu.loadgen — deterministic arrival-process load generation.

The "millions of users" north star needs traffic that looks like users:
requests arriving on a clock (not as fast as a driver can submit),
rates that swing and spike, mixed tenants with different priorities,
and heavy-tailed prompt/output lengths. This package builds such
traces — **bit-identically reproducible from one explicit seed** — and
replays them against the serving Router in (scaled) real time:

- `arrivals`: Poisson / diurnal / burst (flash-crowd) arrival
  schedules, realized by Lewis–Shedler thinning.
- `lengths`: lognormal and empirical-histogram length distributions.
- `trace`: `make_trace(schedule, duration_s, seed, ...)` — arrivals ×
  tenants × lengths into a list of `TraceRequest`s; `validate_trace`
  checks every request fits the engine geometry up front.
- `replay`: `LoadReplayer` drives a Router (and optionally an
  `serving.Autoscaler`) through the trace and reports what users felt:
  TTFT quantiles, p99-TTFT SLO attainment, and — the hardware-honesty
  denominator — replica-seconds occupied.

    from paddle_tpu import loadgen
    trace = loadgen.make_trace(
        loadgen.DiurnalSchedule(2.0, 20.0, period_s=60), 60.0, seed=7,
        prompt_lengths=loadgen.LognormalLengths(12, 0.6, 4, 48),
        output_lengths=loadgen.FixedLength(8),
        tenants=[loadgen.TenantClass('paid', 1, 0),
                 loadgen.TenantClass('free', 3, 2)])
    report = loadgen.LoadReplayer(router, trace,
                                  autoscaler=scaler).run().report(0.5)

Everything is host-side stdlib+numpy — no jax, no device — so traces
generate anywhere and replays measure the fleet, not the generator.
"""
from __future__ import annotations

from .arrivals import (ArrivalSchedule, BurstSchedule, DiurnalSchedule,
                       PoissonSchedule, arrival_times)
from .lengths import (EmpiricalLengths, FixedLength, LengthDistribution,
                      LognormalLengths)
from .trace import (TenantClass, TraceRequest, make_trace, trace_stats,
                    validate_trace)
from .replay import LoadReplayer, ReplayOutcome, ReplayReport

__all__ = [
    'ArrivalSchedule', 'PoissonSchedule', 'DiurnalSchedule',
    'BurstSchedule', 'arrival_times',
    'LengthDistribution', 'FixedLength', 'LognormalLengths',
    'EmpiricalLengths',
    'TenantClass', 'TraceRequest', 'make_trace', 'validate_trace',
    'trace_stats',
    'LoadReplayer', 'ReplayOutcome', 'ReplayReport',
]
