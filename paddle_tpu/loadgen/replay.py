"""Replay a trace against a Router in real time, and report what the
user felt.

The `LoadReplayer` is the closed loop's driver: it submits each
`TraceRequest` when its arrival instant comes due (scaled by
`time_scale`, so a 60 s trace can replay in 6 s on CPU), steps the
router between arrivals so decode keeps advancing, polls the
autoscaler (when one is attached) once per loop iteration, and records
per-request outcomes — accepted/shed/failed, TTFT — plus the
*replica-second* integral: how much hardware the fleet occupied while
serving the trace. Replica-seconds count every ATTACHED replica,
draining ones included — a draining replica still owns its chips until
it is removed, and honest per-hardware SLO math must charge for it.

The report answers the bench's headline question: p99-TTFT SLO
attainment per replica-hour — attainment counted against every
OFFERED request (a shed request is a miss the user felt; grading only
admitted work would let an aggressive shedder look perfect).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Sequence

from ..serving.tenancy import AdmissionRejected
from ..serving.api import FAILED, FINISHED, SamplingParams
from .trace import TraceRequest

NO_EOS = -1


@dataclasses.dataclass
class ReplayOutcome:
    """One trace request's fate."""
    request: TraceRequest
    outcome: str                 # 'completed' | 'shed' | 'failed'
    reason: str = ''             # shed reason / error type
    ttft_s: Optional[float] = None
    tokens: int = 0
    # per-phase seconds from the request ledger (reqledger.PHASES plus
    # 'residual'), when the ledger was enabled during the replay; the
    # report's decomposition columns come from here
    phases: Optional[dict] = None


def _reap_phases(h) -> Optional[dict]:
    """Pull the finalized phase waterfall off a handle's ledger record
    (None when the ledger is disabled or the record never finalized)."""
    rec = getattr(h, '_ledger_rec', None)
    if rec is None or rec.t_done is None:
        return None
    summ = rec.summary()
    phases = dict(summ['phases'])
    phases['residual'] = summ['residual_s']
    return phases


class ReplayReport:
    """Per-request outcomes + fleet occupancy, with the SLO math."""

    def __init__(self, outcomes: List[ReplayOutcome], wall_s: float,
                 replica_seconds: float, time_scale: float,
                 truncated: bool = False):
        self.outcomes = outcomes
        self.wall_s = float(wall_s)
        self.replica_seconds = float(replica_seconds)
        self.time_scale = float(time_scale)
        self.truncated = bool(truncated)

    def _ttfts(self) -> List[float]:
        return sorted(o.ttft_s for o in self.outcomes
                      if o.ttft_s is not None)

    def count(self, outcome: str) -> int:
        return sum(1 for o in self.outcomes if o.outcome == outcome)

    @property
    def dropped(self) -> int:
        """Requests that neither completed nor failed/shed TYPED — the
        zero-drop invariant the autoscaler tests pin at 0."""
        return sum(1 for o in self.outcomes
                   if o.outcome not in ('completed', 'shed', 'failed'))

    def slo_attainment(self, slo_ttft_s: float) -> float:
        """Fraction of OFFERED requests that completed with
        TTFT <= SLO. Shed and failed requests are misses."""
        if not self.outcomes:
            return 1.0
        good = sum(1 for o in self.outcomes
                   if o.outcome == 'completed'
                   and o.ttft_s is not None and o.ttft_s <= slo_ttft_s)
        return good / len(self.outcomes)

    def phase_decomposition(self) -> dict:
        """Per-phase p50/p99/mean seconds across outcomes that carry a
        ledger waterfall — the report's "where did the time go" columns.
        Empty when the request ledger was disabled during the replay."""
        books = [o.phases for o in self.outcomes if o.phases]
        if not books:
            return {}
        names = sorted({p for b in books for p in b})
        out = {}
        for p in names:
            vals = sorted(b.get(p, 0.0) for b in books)
            n = len(vals)
            out[p] = {
                'p50_s': round(vals[min(int(0.50 * n), n - 1)], 6),
                'p99_s': round(vals[min(int(0.99 * n), n - 1)], 6),
                'mean_s': round(sum(vals) / n, 6),
            }
        return out

    def report(self, slo_ttft_s: float) -> dict:
        ttfts = self._ttfts()

        def q(p):
            if not ttfts:
                return None
            return round(ttfts[min(int(p * len(ttfts)),
                                   len(ttfts) - 1)], 4)

        attainment = self.slo_attainment(slo_ttft_s)
        rep_hours = self.replica_seconds / 3600.0
        phases = self.phase_decomposition()
        return {
            'offered': len(self.outcomes),
            'completed': self.count('completed'),
            'shed': self.count('shed'),
            'failed': self.count('failed'),
            'dropped': self.dropped,
            'tokens': sum(o.tokens for o in self.outcomes),
            'wall_s': round(self.wall_s, 3),
            'ttft_p50_s': q(0.50),
            'ttft_p99_s': q(0.99),
            'slo_ttft_s': slo_ttft_s,
            'slo_attainment': round(attainment, 4),
            'replica_seconds': round(self.replica_seconds, 3),
            'attainment_per_replica_hour':
                round(attainment / rep_hours, 2) if rep_hours > 0
                else None,
            'truncated': self.truncated,
            # per-phase latency decomposition (request ledger); {} when
            # the ledger was off — the headline numbers above never
            # depend on it
            'phases': phases,
        }


class LoadReplayer:
    """Drive one trace through a Router (and optionally an Autoscaler).

    Args:
        router: the serving Router to submit into.
        trace: sorted TraceRequests (make_trace output).
        autoscaler: optional serving.Autoscaler; polled once per loop
            iteration — the replayer is the policy loop's clock, the
            way a serving frontend's event loop would be.
        time_scale: multiply trace arrival instants by this (0.1 ⇒
            replay 10x faster than recorded).
        max_wall_s: hard safety bound on the replay (a wedged fleet
            must fail the test, not hang it); sets `truncated`.
        clock/sleep: injectable for tests.
    """

    def __init__(self, router, trace: Sequence[TraceRequest],
                 autoscaler=None, time_scale: float = 1.0,
                 max_wall_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if time_scale <= 0:
            raise ValueError('time_scale must be positive')
        self.router = router
        self.trace = list(trace)
        self.autoscaler = autoscaler
        self.time_scale = float(time_scale)
        self.max_wall_s = max_wall_s
        self._clock = clock
        self._sleep = sleep

    def run(self) -> ReplayReport:
        router = self.router
        outcomes: List[ReplayOutcome] = []
        live: List[tuple] = []       # (TraceRequest, RouterHandle)
        t0 = self._clock()
        last = t0
        replica_seconds = 0.0
        truncated = False
        i = 0
        n = len(self.trace)
        while True:
            now = self._clock()
            replica_seconds += len(router.replicas) * (now - last)
            last = now
            if self.max_wall_s is not None and now - t0 > self.max_wall_s:
                truncated = True
                break
            # submit everything that has come due
            while i < n and (now - t0) >= \
                    self.trace[i].arrival_s * self.time_scale:
                req = self.trace[i]
                i += 1
                try:
                    h = router.submit(
                        list(req.prompt_tokens),
                        SamplingParams(max_new_tokens=req.max_new_tokens,
                                       eos_token_id=NO_EOS),
                        tenant=req.tenant, priority=req.priority,
                        adapter_id=getattr(req, 'adapter', None))
                    live.append((req, h))
                except AdmissionRejected as exc:
                    outcomes.append(ReplayOutcome(
                        req, 'shed', reason=exc.reason))
            if self.autoscaler is not None:
                self.autoscaler.poll()
            router.step()
            # reap finished handles into outcomes
            if live:
                still = []
                for req, h in live:
                    if not h.done:
                        still.append((req, h))
                    elif h.status == FAILED:
                        outcomes.append(ReplayOutcome(
                            req, 'failed',
                            reason=type(h.error).__name__
                            if h.error is not None else 'untyped',
                            tokens=len(h.tokens),
                            phases=_reap_phases(h)))
                    else:
                        outcomes.append(ReplayOutcome(
                            req, 'completed', ttft_s=h.ttft,
                            tokens=len(h.tokens),
                            phases=_reap_phases(h)))
                live = still
            if i >= n and not live:
                break
            if i < n and not live and not any(
                    r.engine.has_work for r in router.replicas):
                # idle gap before the next arrival: sleep a slice of it
                # instead of hot-spinning (the autoscaler still polls
                # every iteration, so cap the slice)
                due = t0 + self.trace[i].arrival_s * self.time_scale
                gap = due - self._clock()
                if gap > 0:
                    self._sleep(min(gap, 0.005))
        for req, h in live:   # truncated: record what never finished
            if h.status == FINISHED:
                out = 'completed'
            elif h.status == FAILED:
                out = 'failed'
            else:
                out = 'dangling'   # counts in ReplayReport.dropped
            outcomes.append(ReplayOutcome(
                req, out, ttft_s=h.ttft, tokens=len(h.tokens),
                phases=_reap_phases(h)))
        outcomes.sort(key=lambda o: o.request.index)
        return ReplayReport(outcomes, self._clock() - t0,
                            replica_seconds, self.time_scale,
                            truncated=truncated)
