"""Deterministic trace construction: arrivals × tenants × lengths.

``make_trace`` is the single entry point: one explicit seed drives ONE
``numpy.random.RandomState`` through a fixed draw order — arrival
instants first (thinning), then per-request (tenant, prompt length,
output length, prompt token ids) — so the same arguments always
produce a bit-identical trace. That is what makes an autoscaling bench
honest: the static-fleet arm and the autoscaled arm replay the SAME
requests at the SAME instants, and a rerun three PRs later replays
them again.

Tenants model the mixed traffic the router's QoS layer exists for:
each ``TenantClass`` carries a selection weight and the priority class
its requests submit under (paid traffic HIGH, best-effort LOW — the
priorities `serving.tenancy` maps to shedding and admission order).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..serving.api import PRIORITY_NORMAL
from .arrivals import ArrivalSchedule, arrival_times
from .lengths import FixedLength, LengthDistribution


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One traffic class: selection weight + the priority its requests
    carry (serving.api.PRIORITY_HIGH/NORMAL/LOW).

    `adapters` is the tenant's LoRA adapter mix as
    ((adapter_id | None, weight), ...): each request drawn for this
    tenant picks one entry by weight (None = the base model). Empty
    means pure base traffic AND consumes no RNG draw, so traces built
    before adapter mixes existed stay bit-identical."""
    name: str = 'default'
    weight: float = 1.0
    priority: int = PRIORITY_NORMAL
    adapters: Tuple[Tuple[Optional[str], float], ...] = ()

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError('tenant weight must be positive')
        for entry in self.adapters:
            if len(entry) != 2 or float(entry[1]) <= 0:
                raise ValueError(
                    f'adapter mix entries must be (adapter_id, '
                    f'positive weight); got {entry!r}')


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One scheduled request. `prompt_tokens` is a tuple so the trace
    is hashable/immutable — replaying must not mutate it."""
    index: int
    arrival_s: float
    tenant: str
    priority: int
    prompt_tokens: Tuple[int, ...]
    max_new_tokens: int
    adapter: Optional[str] = None


def make_trace(schedule: ArrivalSchedule, duration_s: float, seed: int,
               prompt_lengths: LengthDistribution,
               output_lengths: Optional[LengthDistribution] = None,
               tenants: Optional[Sequence[TenantClass]] = None,
               vocab_size: int = 256) -> List[TraceRequest]:
    """Build the full request schedule for one run.

    Token ids are drawn uniformly from [1, vocab_size) (0 is reserved —
    many models pad with it), so a trace binds to any model with at
    least `vocab_size` tokens. Determinism: everything below consumes
    `RandomState(seed)` in one fixed order; equal arguments ⇒
    bit-identical traces (tier-1-tested).
    """
    if vocab_size < 2:
        raise ValueError('vocab_size must be >= 2')
    rng = np.random.RandomState(int(seed))
    output_lengths = output_lengths or FixedLength(8)
    tenant_list = list(tenants) if tenants else [TenantClass()]
    names = sorted({t.name for t in tenant_list})
    if len(names) != len(tenant_list):
        raise ValueError('tenant names must be unique')
    weights = np.array([t.weight for t in tenant_list], dtype=np.float64)
    cdf = np.cumsum(weights / weights.sum())

    instants = arrival_times(schedule, duration_s, rng)
    out: List[TraceRequest] = []
    for i, at in enumerate(instants):
        u = float(rng.random_sample())
        ti = int(np.searchsorted(cdf, u, side='right')) if u < cdf[-1] \
            else len(tenant_list) - 1
        tenant = tenant_list[ti]
        plen = prompt_lengths.sample(rng)
        olen = output_lengths.sample(rng)
        toks = tuple(int(v) for v in rng.randint(1, vocab_size, size=plen))
        # adapter draw comes LAST and only for tenants that declare a
        # mix: pre-adapter traces (and base-only tenants) consume the
        # exact same RNG stream as before, so they stay bit-identical
        adapter = None
        if tenant.adapters:
            aw = np.array([float(w) for _, w in tenant.adapters],
                          dtype=np.float64)
            acdf = np.cumsum(aw / aw.sum())
            au = float(rng.random_sample())
            ai = int(np.searchsorted(acdf, au, side='right')) \
                if au < acdf[-1] else len(tenant.adapters) - 1
            adapter = tenant.adapters[ai][0]
        out.append(TraceRequest(index=i, arrival_s=float(at),
                                tenant=tenant.name,
                                priority=int(tenant.priority),
                                prompt_tokens=toks,
                                max_new_tokens=int(olen),
                                adapter=adapter))
    return out


def validate_trace(trace: Sequence[TraceRequest], max_length: int,
                   headroom: int = 0) -> None:
    """Fail FAST if any request cannot fit an engine's slot length
    (prompt + budget + optional speculation headroom): a trace that
    would raise mid-replay makes every downstream 'zero dropped
    requests' assertion meaningless."""
    for r in trace:
        need = len(r.prompt_tokens) + r.max_new_tokens + headroom
        if need > max_length:
            raise ValueError(
                f'trace request {r.index} needs {need} slot tokens '
                f'(prompt {len(r.prompt_tokens)} + output '
                f'{r.max_new_tokens} + headroom {headroom}) > '
                f'max_length {max_length}')


def trace_stats(trace: Sequence[TraceRequest]) -> dict:
    """Shape summary (bench JSON reports this next to the results)."""
    if not trace:
        return {'requests': 0}
    plens = [len(r.prompt_tokens) for r in trace]
    olens = [r.max_new_tokens for r in trace]
    by_tenant: dict = {}
    by_adapter: dict = {}
    for r in trace:
        by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
        ad = getattr(r, 'adapter', None)
        if ad is not None:
            by_adapter[ad] = by_adapter.get(ad, 0) + 1
    extra = {'by_adapter': by_adapter} if by_adapter else {}
    return {
        **extra,
        'requests': len(trace),
        'span_s': round(trace[-1].arrival_s - trace[0].arrival_s, 3),
        'prompt_tokens': int(sum(plens)),
        'output_tokens': int(sum(olens)),
        'prompt_len_mean': round(float(np.mean(plens)), 1),
        'prompt_len_max': int(max(plens)),
        'output_len_mean': round(float(np.mean(olens)), 1),
        'by_tenant': by_tenant,
    }
