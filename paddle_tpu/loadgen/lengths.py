"""Prompt/output length distributions.

Real traffic is not 24 identical prompts: prompt lengths are heavy-
tailed (a lognormal body is the standard fit for chat traffic — most
prompts short, a long tail of document-stuffed ones) and production
traces come with *measured* histograms worth replaying exactly. Both
shapes live here behind one two-method interface: ``sample(rng)``
draws one integer length from an explicit ``RandomState`` (the
determinism contract — no hidden global RNG), ``bounds()`` reports the
support so trace construction can validate against an engine's
``max_length`` before a single request is submitted.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


class LengthDistribution:
    """One integer-valued sampling distribution."""

    def sample(self, rng) -> int:
        raise NotImplementedError

    def bounds(self) -> Tuple[int, int]:
        """(min, max) achievable value — trace validation reads this."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {'kind': type(self).__name__}


class FixedLength(LengthDistribution):
    def __init__(self, n: int):
        if n < 1:
            raise ValueError('length must be >= 1')
        self.n = int(n)

    def sample(self, rng) -> int:
        return self.n

    def bounds(self) -> Tuple[int, int]:
        return (self.n, self.n)

    def describe(self) -> dict:
        return {'kind': 'fixed', 'n': self.n}


class LognormalLengths(LengthDistribution):
    """Heavy-tailed lengths: ``round(median * exp(sigma * N(0,1)))``
    clipped into [lo, hi]. `median` is the UN-clipped median (the
    lognormal's exp(mu)); clipping moves mass onto the bounds rather
    than re-normalizing, which is what an engine with a hard
    `max_length` actually does to real traffic."""

    def __init__(self, median: float, sigma: float, lo: int, hi: int):
        if median <= 0 or sigma < 0:
            raise ValueError('median must be > 0 and sigma >= 0')
        if not 1 <= lo <= hi:
            raise ValueError('need 1 <= lo <= hi')
        self.median = float(median)
        self.sigma = float(sigma)
        self.lo = int(lo)
        self.hi = int(hi)

    def sample(self, rng) -> int:
        v = self.median * float(np.exp(self.sigma * rng.standard_normal()))
        return int(np.clip(int(round(v)), self.lo, self.hi))

    def bounds(self) -> Tuple[int, int]:
        return (self.lo, self.hi)

    def describe(self) -> dict:
        return {'kind': 'lognormal', 'median': self.median,
                'sigma': self.sigma, 'lo': self.lo, 'hi': self.hi}


class EmpiricalLengths(LengthDistribution):
    """Replay a measured histogram exactly: ``{length: weight}`` with
    arbitrary positive weights (counts or probabilities — normalized
    here). Sampling inverts the CDF with one uniform draw, so the
    stream consumption is one value per sample regardless of bin
    count (determinism depends on a FIXED draw order)."""

    def __init__(self, histogram: Dict[int, float]):
        if not histogram:
            raise ValueError('histogram must be non-empty')
        items = sorted((int(k), float(v)) for k, v in histogram.items())
        if any(k < 1 for k, _ in items):
            raise ValueError('lengths must be >= 1')
        if any(v < 0 for _, v in items) or not any(v > 0 for _, v in items):
            raise ValueError('weights must be >= 0 with a positive total')
        self.values = np.array([k for k, _ in items], dtype=np.int64)
        w = np.array([v for _, v in items], dtype=np.float64)
        self.probs = w / w.sum()
        self._cdf = np.cumsum(self.probs)

    def sample(self, rng) -> int:
        u = float(rng.random_sample())
        return int(self.values[int(np.searchsorted(self._cdf, u,
                                                   side='right'))
                               if u < self._cdf[-1] else len(self.values) - 1])

    def bounds(self) -> Tuple[int, int]:
        return (int(self.values[0]), int(self.values[-1]))

    def describe(self) -> dict:
        return {'kind': 'empirical', 'bins': len(self.values),
                'lo': int(self.values[0]), 'hi': int(self.values[-1])}
