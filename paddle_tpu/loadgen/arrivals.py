"""Arrival processes: when do requests show up?

Every serving bench so far replays a FIXED trace — requests arrive as
fast as the driver can submit them, which measures throughput but says
nothing about latency under the traffic real fleets see: memoryless
request streams (Poisson), slow day/night swings (diurnal), and flash
crowds (burst). This module models arrival RATE as a function of time
and turns it into concrete arrival instants via Lewis–Shedler thinning
(Lewis & Shedler 1979): draw a homogeneous Poisson process at the
schedule's peak rate, keep each candidate with probability
``rate_at(t) / max_rate``. Thinning is exact for any bounded intensity
function and — fed from one seeded ``RandomState`` — fully
deterministic: the same seed replays the same instants bit-identically
(the loadgen determinism contract, tier-1-tested).

Rates are requests/second; schedules are pure host-side math (no jax,
no devices) so traces can be generated anywhere, including inside the
analysis/CI sandbox.
"""
from __future__ import annotations

import math
from typing import List


class ArrivalSchedule:
    """A bounded arrival-intensity function over [0, duration)."""

    #: upper bound on rate_at over the whole horizon (the thinning
    #: envelope); subclasses must set it
    max_rate: float = 0.0

    def rate_at(self, t: float) -> float:
        raise NotImplementedError

    def describe(self) -> dict:
        return {'kind': type(self).__name__, 'max_rate': self.max_rate}


class PoissonSchedule(ArrivalSchedule):
    """Memoryless steady-state traffic at a constant rate."""

    def __init__(self, rate: float):
        if rate <= 0:
            raise ValueError('rate must be positive')
        self.rate = float(rate)
        self.max_rate = self.rate

    def rate_at(self, t: float) -> float:
        return self.rate

    def describe(self) -> dict:
        return {'kind': 'poisson', 'rate': self.rate}


class DiurnalSchedule(ArrivalSchedule):
    """Day/night swing: a raised cosine from `base_rate` (trough, at
    t=0 with phase=0) to `peak_rate` (half a period later), repeating
    every `period_s`. `phase` shifts the cycle in fractions of a period
    (phase=0.5 starts at the peak)."""

    def __init__(self, base_rate: float, peak_rate: float,
                 period_s: float, phase: float = 0.0):
        if base_rate < 0 or peak_rate < base_rate:
            raise ValueError('need 0 <= base_rate <= peak_rate')
        if period_s <= 0:
            raise ValueError('period_s must be positive')
        self.base_rate = float(base_rate)
        self.peak_rate = float(peak_rate)
        self.period_s = float(period_s)
        self.phase = float(phase)
        self.max_rate = self.peak_rate

    def rate_at(self, t: float) -> float:
        x = t / self.period_s + self.phase
        swing = 0.5 * (1.0 - math.cos(2.0 * math.pi * x))
        return self.base_rate + (self.peak_rate - self.base_rate) * swing

    def describe(self) -> dict:
        return {'kind': 'diurnal', 'base_rate': self.base_rate,
                'peak_rate': self.peak_rate, 'period_s': self.period_s,
                'phase': self.phase}


class BurstSchedule(ArrivalSchedule):
    """Flash crowd: steady `base_rate` with a rectangular spike to
    `burst_rate` during [burst_start_s, burst_start_s + burst_len_s)."""

    def __init__(self, base_rate: float, burst_rate: float,
                 burst_start_s: float, burst_len_s: float):
        if base_rate < 0 or burst_rate < base_rate:
            raise ValueError('need 0 <= base_rate <= burst_rate')
        if burst_len_s <= 0:
            raise ValueError('burst_len_s must be positive')
        self.base_rate = float(base_rate)
        self.burst_rate = float(burst_rate)
        self.burst_start_s = float(burst_start_s)
        self.burst_len_s = float(burst_len_s)
        self.max_rate = self.burst_rate

    def rate_at(self, t: float) -> float:
        if self.burst_start_s <= t < self.burst_start_s + self.burst_len_s:
            return self.burst_rate
        return self.base_rate

    def describe(self) -> dict:
        return {'kind': 'burst', 'base_rate': self.base_rate,
                'burst_rate': self.burst_rate,
                'burst_start_s': self.burst_start_s,
                'burst_len_s': self.burst_len_s}


def arrival_times(schedule: ArrivalSchedule, duration_s: float,
                  rng) -> List[float]:
    """Concrete arrival instants in [0, duration_s), sorted, via
    thinning against `schedule.max_rate`. Deterministic for a given
    `rng` state: draws consume the stream in one fixed order
    (exponential gap, then the acceptance uniform), so the same seed
    yields bit-identical instants."""
    if duration_s <= 0:
        raise ValueError('duration_s must be positive')
    lam = float(schedule.max_rate)
    if lam <= 0:
        return []
    out: List[float] = []
    t = 0.0
    while True:
        # exponential inter-arrival of the ENVELOPE process
        t += -math.log(1.0 - float(rng.random_sample())) / lam
        if t >= duration_s:
            return out
        if float(rng.random_sample()) * lam <= schedule.rate_at(t):
            out.append(t)
