"""paddle.jit — to_static + donated jitted TrainStep.

Upstream: python/paddle/jit/ (ProgramTranslator → static graph). The
TPU-native design needs no custom IR: a Layer is *functionalized* — its
parameter/buffer pytree is pulled out (`functional_state`), the forward is
re-run with traced values bound in (`functional_call`) under
`autograd.functional_scope()` (tape off, ops stay pure jax), and the whole
training step is one `jax.jit` with params/opt-state/buffers donated, so
XLA updates weights in place in HBM. RNG inside the trace comes from
`Generator.trace_scope` keyed by the step counter — dropout is
deterministic per step and replays identically on recompilation.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
# submodule import: jax.export is not an attribute of the jax module
# object on older jax (0.4.x), but the submodule itself is importable
from jax import export as _jax_export
import jax.numpy as jnp
import numpy as np

from .. import autograd, framework
from .. import observability as _obs
from .. import programs as _programs
from ..programs import ProgramDeserializeError
from ..nn.layer import Layer
from ..tensor import Tensor

_tree = jax.tree_util


class InputSpec:
    """Shape/dtype spec (upstream: paddle.static.InputSpec); None dims are
    dynamic-batch buckets — each concrete size triggers one compilation."""

    def __init__(self, shape, dtype='float32', name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f'InputSpec(shape={self.shape}, dtype={self.dtype})'


def functional_state(layer: Layer):
    """Pull (params, buffers) as flat {name: raw jax array} dicts."""
    params = {n: p.value for n, p in layer.named_parameters()
              if not p.stop_gradient}
    frozen = {n: p.value for n, p in layer.named_parameters()
              if p.stop_gradient}
    buffers = {n: b.value for n, b in layer.named_buffers()}
    return params, frozen, buffers


def _bind(layer: Layer, params, frozen, buffers):
    """Swap traced values into the live tensors; returns restore info."""
    saved = []
    pmap = dict(layer.named_parameters())
    bmap = dict(layer.named_buffers())
    for name, val in {**params, **frozen}.items():
        t = pmap[name]
        saved.append((t, t._data, t._node))
        t._data = val
        t._node = None
    for name, val in buffers.items():
        t = bmap[name]
        saved.append((t, t._data, t._node))
        t._data = val
        t._node = None
    return saved, bmap


def _unbind(saved):
    for t, data, node in saved:
        t._data = data
        t._node = node


def functional_call(layer: Layer, params, frozen, buffers, args, kwargs,
                    rng_key=None):
    """Run layer's forward with the given state bound in, purely.

    Returns (output pytree of raw values, new buffer dict) — buffer
    mutations (BN running stats) are captured as outputs.
    """
    return functional_method(layer, '__call__', params, frozen, buffers,
                             args, kwargs, rng_key=rng_key)


def functional_method(layer: Layer, method: str, params, frozen, buffers,
                      args, kwargs, rng_key=None):
    """Like functional_call but invokes an arbitrary method of the layer
    (e.g. an encoder-decoder model's `encode` during generation)."""
    saved, bmap = _bind(layer, params, frozen, buffers)
    try:
        ctx = framework.default_generator.trace_scope(rng_key) \
            if rng_key is not None else _null_ctx()
        with ctx, autograd.functional_scope():
            wrapped_args = _tree.tree_map(
                lambda v: Tensor(v) if not isinstance(v, Tensor) else v, args)
            out = getattr(layer, method)(*wrapped_args, **kwargs)
        out_vals = _tree.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
        new_buffers = {n: bmap[n]._data for n in buffers}
        return out_vals, new_buffers
    finally:
        _unbind(saved)


@contextlib.contextmanager
def _null_ctx():
    yield


class StaticLayer:
    """A Layer (or function) compiled to one XLA program per (input shape,
    static-kwargs) combination (the product of @to_static). Tensor/array
    kwargs are traced; python-value kwargs are compile-time constants
    keyed into the jit cache."""

    def __init__(self, fn_or_layer, input_spec=None):
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._is_layer = isinstance(fn_or_layer, Layer)
        self._jit_cache: Dict[Any, Any] = {}

    def _check_spec(self, args):
        if not self._input_spec:
            return
        for i, (spec, a) in enumerate(zip(self._input_spec, args)):
            shape = tuple(np.shape(a))
            if len(shape) != len(spec.shape) or any(
                    s is not None and s != d
                    for s, d in zip(spec.shape, shape)):
                raise ValueError(
                    f'input {i} shape {shape} does not match InputSpec '
                    f'{spec.shape} (None dims are dynamic)')

    def _get_jitted(self, static_kwargs):
        try:
            key = tuple(sorted(
                (k, type(v).__name__, v) for k, v in static_kwargs.items()))
            hash(key)
        except TypeError:
            raise TypeError(
                f'to_static kwargs must be Tensors/arrays (traced) or '
                f'hashable python values (compile-time constants); got '
                f'{ {k: type(v).__name__ for k, v in static_kwargs.items()} }')
        f = self._jit_cache.get(key)
        if f is not None:
            return f
        if self._is_layer:
            def fn(params, frozen, buffers, rkey, args, tkwargs):
                kw = {k: Tensor(v) for k, v in tkwargs.items()}
                kw.update(static_kwargs)
                return functional_call(self._target, params, frozen,
                                       buffers, args, kw, rng_key=rkey)
        else:
            def fn(rkey, args, tkwargs):
                with framework.default_generator.trace_scope(rkey), \
                        autograd.functional_scope():
                    wrapped = _tree.tree_map(lambda v: Tensor(v), args)
                    kw = {k: Tensor(v) for k, v in tkwargs.items()}
                    kw.update(static_kwargs)
                    out = self._target(*wrapped, **kw)
                return _tree.tree_map(
                    lambda t: t.value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
        target_name = getattr(self._target, '__name__',
                              type(self._target).__name__)
        f = _programs.get_store().wrap_jit(
            fn, name=f'to_static:{target_name}', kind='to_static',
            statics={'target': target_name,
                     'src': _programs.code_token(self._target),
                     'static_kwargs': repr(key)})
        self._jit_cache[key] = f
        # executable-cache telemetry: compile count/seconds ride the
        # jax.monitoring listeners (observability.telemetry); the
        # python-side cache growth is recorded here
        _obs.note_jit_cache_entry('to_static')
        return f

    def __call__(self, *args, **kwargs):
        self._check_spec(args)
        arg_vals = _tree.tree_map(
            lambda v: v.value if isinstance(v, Tensor) else jnp.asarray(v),
            args, is_leaf=lambda v: isinstance(v, Tensor))
        traced_kw = {k: (v.value if isinstance(v, Tensor)
                         else jnp.asarray(v))
                     for k, v in kwargs.items()
                     if isinstance(v, (Tensor, jax.Array, np.ndarray))}
        static_kw = {k: v for k, v in kwargs.items() if k not in traced_kw}
        jitted = self._get_jitted(static_kw)
        key = framework.next_rng_key()
        if self._is_layer:
            params, frozen, buffers = functional_state(self._target)
            out_vals, new_bufs = jitted(params, frozen, buffers, key,
                                        arg_vals, traced_kw)
            bmap = dict(self._target.named_buffers())
            for n, v in new_bufs.items():
                bmap[n]._data = v
        else:
            out_vals = jitted(key, arg_vals, traced_kw)
        return _tree.tree_map(Tensor, out_vals)

    # passthroughs so a converted Layer still looks like one
    def __getattr__(self, name):
        return getattr(self._target, name)


def to_static(function=None, input_spec=None, full_graph=True, **kwargs):
    """Convert a Layer or function to a compiled static form."""
    def deco(f):
        if isinstance(f, Layer):
            return StaticLayer(f, input_spec)
        wrapper = StaticLayer(f, input_spec)
        functools.update_wrapper(wrapper, f,
                                 assigned=('__name__', '__doc__'),
                                 updated=())
        return wrapper
    return deco(function) if function is not None else deco


class TrainStep:
    """One donated, jitted training step (upstream analogue: the
    to_static-converted train loop body; SURVEY.md §3 'Jitted train step').

    step(params, opt_state, buffers, key, lr, batch) compiles once per batch
    shape; params/opt_state/buffers are donated so XLA aliases them in HBM.
    """

    def __init__(self, layer: Layer, loss_fn: Callable, optimizer,
                 extra_metrics: Optional[Callable] = None):
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._opt_state = None
        self._step_key_root = framework.default_generator.root_key
        self._n_calls = 0
        self.compile_count = 0

        def loss_and_grads(params, buffers, frozen, key, batch):
            self.compile_count += 1  # python-level: counts traces, not runs
            _obs.note_jit_cache_entry('train_step')  # one entry per trace

            def loss_of(pv):
                inputs, labels = batch
                out, new_bufs = functional_call(
                    self.layer, pv, frozen, buffers,
                    inputs if isinstance(inputs, tuple) else (inputs,), {},
                    rng_key=key)
                with autograd.functional_scope():
                    wrapped_out = _tree.tree_map(Tensor, out)
                    wrapped_lab = _tree.tree_map(
                        lambda v: Tensor(v) if not isinstance(v, Tensor)
                        else v, labels)
                    loss_t = self.loss_fn(wrapped_out, wrapped_lab)
                loss_v = loss_t.value if isinstance(loss_t, Tensor) else loss_t
                return loss_v, new_bufs
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            return loss, grads, new_bufs

        def step_fn(params, opt_state, buffers, frozen, key, lr, batch):
            loss, grads, new_bufs = loss_and_grads(
                params, buffers, frozen, key, batch)
            new_params, new_opt = self.optimizer.apply_gradients(
                grads, params, opt_state, lr)
            return loss, new_params, new_opt, new_bufs

        # the persistent key must see what the avals cannot: the layer
        # and loss bodies and the optimizer's baked-in hyperparameters
        # (two Adams with different betas share every input aval)
        step_statics = {
            'layer': type(layer).__qualname__,
            'layer_src': _programs.code_token(type(layer)),
            'loss_src': _programs.code_token(loss_fn),
            'optimizer': _programs.describe_statics(optimizer),
        }
        self._offload = getattr(optimizer, '_offload', None) == 'host'
        if self._offload:
            # host-offloaded optimizer state: jit ONLY the grad step
            # (params persist in HBM, no donation); the update streams
            # per-leaf through optimizer.offload.OffloadEngine
            from ..optimizer.offload import OffloadEngine

            self._jitted_grads = _programs.get_store().wrap_jit(
                loss_and_grads,
                name='train_step_grads', kind='train',
                statics=step_statics, donate_argnums=(1,))
            self._engine = OffloadEngine(optimizer)
        # enrolled in the program store: the one AOT compile (or warm
        # disk load) serves the traffic AND yields cost/memory analysis
        # for top_programs(). The store owns the jit AND the donation:
        # the direct path donates params/opt-state/buffers as before
        # (in-process compile — the PR-8-safe case), while the
        # persisted/export path re-applies the donation only on a
        # gauntlet-safe verdict (donation.py) — that flip is what drops
        # the transient 2x train-state buffering of the undonated
        # store posture.
        self._jitted = _programs.get_store().wrap_jit(
            step_fn,
            name='train_step', kind='train', statics=step_statics,
            donate_argnums=(0, 1, 2))

    @property
    def donation_live(self) -> bool:
        """True when this step's executable aliases its donated buffers
        in place — i.e. train state is NOT paying the undonated store
        path's transient 2x buffering. The direct (non-persistent)
        path always donates; the store-served path donates only on a
        donation-gauntlet-safe verdict."""
        store = _programs.get_store()
        return (not store.persistent) or store.donation_enabled

    @staticmethod
    def _as_batch(inputs, labels):
        return (
            _tree.tree_map(lambda v: v.value if isinstance(v, Tensor)
                           else jnp.asarray(v), inputs,
                           is_leaf=lambda v: isinstance(v, Tensor)),
            _tree.tree_map(lambda v: v.value if isinstance(v, Tensor)
                           else jnp.asarray(v), labels,
                           is_leaf=lambda v: isinstance(v, Tensor)))

    def memory_analysis(self, inputs, labels):
        """XLA's CompiledMemoryStats for the step at these batch shapes
        (peak_memory_in_bytes, temp/argument/output sizes). The AOT
        lower().compile() hits the jit cache, so after the step has run
        once this costs no recompile."""
        params, frozen, buffers = functional_state(self.layer)
        key = jax.random.fold_in(self._step_key_root, 0)
        if self._offload:
            # offload path: HBM peak is the grad step (slots stream
            # through one leaf at a time and never sit in HBM)
            return self._jitted_grads.lower(
                params, buffers, frozen, key,
                self._as_batch(inputs, labels)).compile().memory_analysis()
        if self._opt_state is None:
            self._opt_state = self.optimizer.init_state(params)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return self._jitted.lower(
            params, self._opt_state, buffers, frozen, key, lr,
            self._as_batch(inputs, labels)).compile().memory_analysis()

    def __call__(self, inputs, labels):
        # the span is the goodput ledger's `step_compute` source (first
        # call: the trace/compile inside is re-attributed to `compile`
        # by the ledger's nested-interval subtraction)
        with _obs.span('train.step'):
            params, frozen, buffers = functional_state(self.layer)
            if self._opt_state is None and not self._offload:
                self._opt_state = self.optimizer.init_state(params)
            key = jax.random.fold_in(self._step_key_root, self._n_calls)
            self._n_calls += 1
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            batch = self._as_batch(inputs, labels)
            if self._offload:
                if self._opt_state is None:
                    self._opt_state = self._engine.init_state(params)
                loss, grads, new_bufs = self._jitted_grads(
                    params, buffers, frozen, key, batch)
                new_params, self._opt_state = self._engine.apply(
                    grads, params, self._opt_state, lr)
            else:
                loss, new_params, self._opt_state, new_bufs = self._jitted(
                    params, self._opt_state, buffers, frozen, key, lr,
                    batch)
            # write back into the live Layer
            pmap = dict(self.layer.named_parameters())
            for n, v in new_params.items():
                pmap[n]._data = v
                pmap[n]._node = None
            bmap = dict(self.layer.named_buffers())
            for n, v in new_bufs.items():
                bmap[n]._data = v
            return Tensor(loss)


class TranslatedLayer:
    """The product of `jit.load(path)` without the original class
    (upstream: paddle.jit.TranslatedLayer from python/paddle/jit/api.py):
    a deserialized StableHLO program closed over restored state. Callable
    like the original (Static)Layer's inference forward."""

    def __init__(self, exported, params, frozen, buffers, manifest):
        self._exported = exported
        self._params = params
        self._frozen = frozen
        self._buffers = buffers
        self._manifest = manifest

    @property
    def input_spec(self):
        return [InputSpec(s['shape'], s['dtype'])
                for s in self._manifest.get('input_spec', [])]

    def named_parameters(self):
        for n, v in {**self._params, **self._frozen}.items():
            yield n, Tensor(v)

    def eval(self):
        return self

    def __call__(self, *args):
        vals = _tree.tree_map(
            lambda v: v.value if isinstance(v, Tensor) else jnp.asarray(v),
            args, is_leaf=lambda v: isinstance(v, Tensor))
        out = self._exported.call(self._params, self._frozen, self._buffers,
                                  *vals)
        return _tree.tree_map(Tensor, out)


def _export_platforms():
    # make the artifact portable across the surfaces this framework runs
    # on: the real chip and the CPU test mesh
    plats = {'tpu', 'cpu'}
    plats.add(jax.default_backend())
    return tuple(sorted(plats))


def save(layer, path, input_spec=None, **config):
    """Serialize a (Static)Layer as a self-contained inference artifact
    (upstream: paddle.jit.save, python/paddle/jit/api.py — Program +
    persistables). TPU-native form: `jax.export` StableHLO bytes
    (`<path>.pdmodel.stablehlo`) + parameters/buffers npz
    (`<path>.pdiparams.npz`). `jit.load(path)` rebuilds a callable from
    the serialized program alone — the original Python class is NOT
    needed. None dims in input_spec export as symbolic (dynamic) dims."""
    import json
    import os
    target = layer._target if isinstance(layer, StaticLayer) else layer
    if input_spec is None and isinstance(layer, StaticLayer):
        input_spec = layer._input_spec
    if not input_spec:
        raise ValueError('jit.save needs input_spec (shapes/dtypes of the '
                         'forward arguments) to trace the program')
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    arrays = {f'param::{n}': np.asarray(p.value)
              for n, p in target.named_parameters()}
    arrays.update({f'buffer::{n}': np.asarray(b.value)
                   for n, b in target.named_buffers()})
    np.savez(path + '.pdiparams.npz', **arrays)

    # the serialized program is the EVAL forward (a deployment artifact:
    # dropout off, BN in inference mode), matching upstream jit.save
    was_training = target.training
    target.eval()
    try:
        params, frozen, buffers = functional_state(target)

        def infer_fn(params, frozen, buffers, *args):
            out, _ = functional_call(target, params, frozen, buffers,
                                     args, {})
            return out

        arg_specs = []
        scope = None
        n_sym = 0
        for s in input_spec:
            dims = []
            has_sym = False
            for d in s.shape:
                if d is None:
                    dims.append(f'b{n_sym}')
                    n_sym += 1
                    has_sym = True
                else:
                    dims.append(str(d))
            if has_sym:
                # one shared scope so symbols across args can relate
                if scope is None:
                    scope = _jax_export.SymbolicScope()
                shape = _jax_export.symbolic_shape(', '.join(dims),
                                                  scope=scope)
            else:
                shape = tuple(int(d) for d in dims)
            arg_specs.append(jax.ShapeDtypeStruct(shape, s.dtype))
        abstract = lambda tree: _tree.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), tree)
        exported = _jax_export.export(
            jax.jit(infer_fn), platforms=_export_platforms())(
            abstract(params), abstract(frozen), abstract(buffers),
            *arg_specs)
        with open(path + '.pdmodel.stablehlo', 'wb') as f:
            f.write(exported.serialize())
    finally:
        if was_training:
            target.train()
    manifest = {
        'class': type(target).__name__,
        'format': 'stablehlo',
        # the exported program's calling convention splits state into
        # (trainable, frozen, buffers) dicts; load must rebuild the same
        # pytrees, so record the partition
        'trainable': sorted(params),
        'frozen': sorted(frozen),
        'input_spec': [
            {'shape': list(s.shape), 'dtype': str(s.dtype)}
            for s in input_spec],
    }
    with open(path + '.pdmodel.json', 'w') as f:
        json.dump(manifest, f)


def load(path, layer=None):
    """Load a `jit.save` artifact. Without `layer`, deserializes the
    StableHLO program and returns a `TranslatedLayer` — no Python class
    required (upstream paddle.jit.load semantics). With `layer`, restores
    state into it (a state-dict fast path)."""
    import json
    import os
    data = np.load(path + '.pdiparams.npz')
    if layer is not None:
        target = layer._target if isinstance(layer, StaticLayer) else layer
        sd = {}
        for k in data.files:
            kind, name = k.split('::', 1)
            sd[name] = data[k]
        target.set_state_dict(sd)
        return layer if isinstance(layer, StaticLayer) else StaticLayer(layer)
    hlo_path = path + '.pdmodel.stablehlo'
    if not os.path.exists(hlo_path):
        raise ValueError(
            f'{hlo_path} not found: this artifact predates program '
            f'serialization — pass the layer instance to restore into')
    with open(hlo_path, 'rb') as f:
        raw = f.read()
    try:
        exported = _jax_export.deserialize(bytearray(raw))
    except Exception as exc:
        # a truncated/garbage artifact used to raise a raw internal
        # exception; the typed error lets callers fall back (re-export,
        # restore-into-layer) instead of crashing
        _obs.emit('program_cache_reject', path=hlo_path,
                  reason='deserialize', error=type(exc).__name__)
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_program_cache_rejects_total',
                'persisted entries rejected at load',
                ('reason',)).labels(reason='deserialize').inc()
        raise ProgramDeserializeError(
            hlo_path, f'{type(exc).__name__}: {exc}') from exc
    params, frozen, buffers = {}, {}, {}
    manifest = {}
    try:
        with open(path + '.pdmodel.json') as f:
            manifest = json.load(f)
    except OSError:
        pass
    frozen_names = set(manifest.get('frozen', []))
    for k in data.files:
        kind, name = k.split('::', 1)
        if kind == 'buffer':
            buffers[name] = jnp.asarray(data[k])
        elif name in frozen_names:
            frozen[name] = jnp.asarray(data[k])
        else:
            params[name] = jnp.asarray(data[k])
    return TranslatedLayer(exported, params, frozen, buffers, manifest)


def not_to_static(fn):
    fn.__jit_skip__ = True
    return fn


def enable_to_static(flag=True):
    pass  # always-on eager→jit conversion path


def ignore_module(modules):
    """Upstream: paddle.jit.ignore_module — marks modules whose calls
    to_static should not transcribe. The tape-based to_static here never
    transcribes python source, so this is a recorded no-op."""
    return None
