"""paddle.onnx (upstream: python/paddle/onnx/export.py, which delegates
to paddle2onnx's Paddle-IR graph walk).

TPU-native design: there is no second IR to convert — the layer's eval
forward is traced ONCE to a jaxpr (the same functionalization
`paddle.jit` uses) and each lax primitive is mapped to an ONNX node.
`dot_general` lowers to ONNX Einsum (opset 12+), which covers every
contraction Linear/attention produce without pattern-matching;
`conv_general_dilated` lowers to Conv. Parameters become initializers,
so the ModelProto is self-contained.

The `onnx` package is only needed for protobuf assembly; when it is not
importable (this offline image), `export` raises a clear gate pointing
at `paddle.jit.save`, whose StableHLO artifact is the framework's
first-class portable format. The converter itself is exercised in tests
through a lightweight in-memory double of the onnx helper API plus a
numpy evaluator of the emitted graph.
"""
from __future__ import annotations

import string
from typing import Any, Dict, List

import jax
import numpy as np
from jax.extend import core as _jex_core

__all__ = ['export']


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Write `layer`'s eval forward as an ONNX ModelProto at `path`.

    input_spec: list of InputSpec (or example Tensors/ndarrays). Dynamic
    (None) dims are materialized at size 1 and exported as symbolic dims;
    note that graphs with internal Reshape ops (e.g. attention head
    splits) bake the example sizes into the reshape targets, so models
    with reshapes should be exported with static shapes.
    """
    onnx_api = configs.pop('_onnx_api', None)
    if onnx_api is None:
        try:
            import onnx as onnx_api  # noqa: F811
        except ImportError as e:
            raise RuntimeError(
                'paddle.onnx.export requires the `onnx` package, which is '
                'not available in this offline build. Use paddle.jit.save('
                'layer, path, input_spec) instead: it writes a '
                'self-contained StableHLO + params artifact that '
                'paddle.jit.load runs on cpu/tpu without the original '
                'model class.') from e
    if not 13 <= int(opset_version) <= 17:
        # the emitted node forms (ReduceSum axes-as-input, Reduce* axes
        # attribute, Einsum, Where) are exactly the opset 13-17 dialect
        raise ValueError(
            f'paddle.onnx.export emits opset 13-17 semantics; '
            f'got opset_version={opset_version}')
    model = build_model(layer, input_spec, opset_version, onnx_api)
    out_path = path if str(path).endswith('.onnx') else str(path) + '.onnx'
    with open(out_path, 'wb') as f:
        f.write(model.SerializeToString())
    return out_path


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

def _example_arrays(input_spec):
    from .jit import InputSpec
    from .tensor import Tensor
    arrays, dyn_axes = [], []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = tuple(1 if s is None else int(s) for s in spec.shape)
            dyn = [i for i, s in enumerate(spec.shape) if s is None]
            dt = np.dtype(spec.dtype if isinstance(spec.dtype, str)
                          else str(spec.dtype))
            arr = np.zeros(shape, dt)
        else:
            arr = spec.numpy() if isinstance(spec, Tensor) \
                else np.asarray(spec)
            dyn = []
        arrays.append(arr)
        dyn_axes.append(dyn)
    return arrays, dyn_axes


def build_model(layer, input_spec, opset_version, onnx_api):
    """Trace layer → jaxpr → ONNX GraphProto → ModelProto."""
    from .jit import functional_state, functional_call

    if input_spec is None:
        raise ValueError('paddle.onnx.export needs input_spec')
    was_training = getattr(layer, 'training', False)
    if hasattr(layer, 'eval'):
        layer.eval()
    try:
        params, frozen, buffers = functional_state(layer)
        state = {**params, **frozen, **buffers}
        arrays, dyn_axes = _example_arrays(input_spec)

        def pure(state_vals, *xs):
            p = {k: state_vals[k] for k in params}
            fz = {k: state_vals[k] for k in frozen}
            bf = {k: state_vals[k] for k in buffers}
            out, _ = functional_call(layer, p, fz, bf, tuple(xs), {})
            return out

        closed = jax.make_jaxpr(pure)(state, *arrays)
    finally:
        if was_training and hasattr(layer, 'train'):
            layer.train()

    # state leaves arrive as flattened invars in dict-key order
    state_keys = sorted(state.keys())
    n_state = len(state_keys)
    conv = _Converter(onnx_api)
    jaxpr = closed.jaxpr
    for i, var in enumerate(jaxpr.invars):
        if i < n_state:
            conv.add_initializer(state_keys[i],
                                 np.asarray(state[state_keys[i]]), var)
        else:
            conv.add_input(f'x{i - n_state}', var,
                           dyn_axes[i - n_state])
    for cvar, cval in zip(jaxpr.constvars, closed.consts):
        conv.add_initializer(conv.fresh('const'), np.asarray(cval), cvar)
    conv.convert(jaxpr)
    outputs = [conv.value(v) for v in jaxpr.outvars]
    return conv.finish(outputs, jaxpr.outvars, opset_version)


# ---------------------------------------------------------------------------
# jaxpr -> ONNX graph
# ---------------------------------------------------------------------------

_DTYPE_TO_ONNX = {
    'float32': 'FLOAT', 'float64': 'DOUBLE', 'float16': 'FLOAT16',
    'bfloat16': 'BFLOAT16', 'int64': 'INT64', 'int32': 'INT32',
    'int16': 'INT16', 'int8': 'INT8', 'uint8': 'UINT8', 'bool': 'BOOL',
}

_UNARY = {
    'exp': 'Exp', 'log': 'Log', 'tanh': 'Tanh', 'abs': 'Abs',
    'neg': 'Neg', 'sqrt': 'Sqrt', 'sign': 'Sign', 'floor': 'Floor',
    'ceil': 'Ceil', 'sin': 'Sin', 'cos': 'Cos', 'erf': 'Erf',
    'logistic': 'Sigmoid', 'is_finite': 'IsInf', 'not': 'Not',
    'round': 'Round',
}

_BINARY = {
    'add': 'Add', 'sub': 'Sub', 'mul': 'Mul', 'div': 'Div',
    'max': 'Max', 'min': 'Min', 'pow': 'Pow',
    'and': 'And', 'or': 'Or', 'xor': 'Xor',
}

_COMPARE = {'eq': 'Equal', 'gt': 'Greater', 'ge': 'GreaterOrEqual',
            'lt': 'Less', 'le': 'LessOrEqual'}

_REDUCE = {'reduce_sum': 'ReduceSum', 'reduce_max': 'ReduceMax',
           'reduce_min': 'ReduceMin', 'reduce_prod': 'ReduceProd'}


class _Converter:
    def __init__(self, onnx_api):
        self.api = onnx_api
        self.nodes: List[Any] = []
        self.initializers: List[Any] = []
        self.inputs: List[Any] = []
        self.names: Dict[Any, str] = {}  # jaxpr Var -> value name
        self._ctr = 0

    # -- naming -------------------------------------------------------------
    def fresh(self, hint='v'):
        self._ctr += 1
        return f'{hint}_{self._ctr}'

    def value(self, v):
        """ONNX value name for a jaxpr atom (Var or Literal)."""
        if isinstance(v, _jex_core.Literal):
            arr = np.asarray(v.val)
            name = self.fresh('lit')
            self.initializers.append(
                self.api.numpy_helper.from_array(arr, name))
            return name
        if v not in self.names:
            self.names[v] = self.fresh()
        return self.names[v]

    def set_name(self, var, name):
        self.names[var] = name

    # -- graph pieces -------------------------------------------------------
    def _elem_type(self, dtype):
        key = _DTYPE_TO_ONNX.get(np.dtype(dtype).name)
        if key is None:
            raise NotImplementedError(
                f'paddle.onnx.export: dtype {dtype} has no ONNX mapping')
        return getattr(self.api.TensorProto, key)

    def add_input(self, name, var, dyn_axes=()):
        shape = [f'dyn_{i}' if i in dyn_axes else int(s)
                 for i, s in enumerate(var.aval.shape)]
        self.inputs.append(self.api.helper.make_tensor_value_info(
            name, self._elem_type(var.aval.dtype), shape))
        self.set_name(var, name)

    def add_initializer(self, name, arr, var=None):
        arr = np.asarray(arr)
        if str(arr.dtype) == 'bfloat16':
            # numpy has no bf16 container: store fp32 and Cast back to
            # BFLOAT16 so the graph stays type-consistent where the
            # traced computation runs in bf16
            self.initializers.append(self.api.numpy_helper.from_array(
                arr.astype(np.float32), name + '_fp32'))
            cast = self.node('Cast', [name + '_fp32'],
                             to=self.api.TensorProto.BFLOAT16)
            if var is not None:
                self.set_name(var, cast)
            return cast
        self.initializers.append(
            self.api.numpy_helper.from_array(arr, name))
        if var is not None:
            self.set_name(var, name)
        return name

    def node(self, op, ins, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(self.api.helper.make_node(op, ins, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def const_i64(self, values, hint='shape'):
        return self.add_initializer(self.fresh(hint),
                                    np.asarray(values, np.int64))

    # -- conversion ---------------------------------------------------------
    def convert(self, jaxpr):
        for eqn in jaxpr.eqns:
            self._eqn(eqn)

    def _eqn(self, eqn):
        prim = eqn.primitive.name
        ins = [self.value(v) for v in eqn.invars]
        out = eqn.outvars[0] if eqn.outvars else None
        p = eqn.params

        if prim in ('pjit', 'jit', 'closed_call', 'core_call',
                    'custom_jvp_call', 'custom_vjp_call',
                    'custom_vjp_call_jaxpr', 'remat', 'checkpoint'):
            inner = p.get('jaxpr') or p.get('call_jaxpr') \
                or p.get('fun_jaxpr')
            if hasattr(inner, 'jaxpr'):  # ClosedJaxpr
                consts, inner = inner.consts, inner.jaxpr
            else:
                consts = ()
            for cvar, cval in zip(inner.constvars, consts):
                self.add_initializer(self.fresh('const'),
                                     np.asarray(cval), cvar)
            for ivar, iname in zip(inner.invars, ins):
                self.set_name(ivar, iname)
            self.convert(inner)
            for ovar, outer in zip(inner.outvars, eqn.outvars):
                self.set_name(outer, self.value(ovar))
            return

        if prim in _UNARY and prim != 'is_finite':
            name = self.node(_UNARY[prim], ins)
        elif prim in _BINARY:
            name = self.node(_BINARY[prim], ins)
        elif prim in _COMPARE:
            name = self.node(_COMPARE[prim], ins)
        elif prim == 'rsqrt':
            name = self.node('Reciprocal', [self.node('Sqrt', ins)])
        elif prim == 'rem':
            # fmod=1 = C truncated remainder (sign of dividend) — lax.rem
            # semantics for both ints and floats
            name = self.node('Mod', ins, fmod=1)
        elif prim == 'square':
            name = self.node('Mul', [ins[0], ins[0]])
        elif prim == 'cbrt':
            # sign(x) * |x|^(1/3): Pow alone NaNs on negative bases
            third = self.add_initializer(
                self.fresh('third'),
                np.asarray(1.0 / 3.0,
                           np.dtype(eqn.invars[0].aval.dtype)))
            mag = self.node('Pow', [self.node('Abs', [ins[0]]), third])
            name = self.node('Mul', [self.node('Sign', [ins[0]]), mag])
        elif prim == 'erfc':
            one = self.add_initializer(
                self.fresh('one'),
                np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
            name = self.node('Sub', [one, self.node('Erf', ins)])
        elif prim == 'integer_pow':
            e = self.add_initializer(
                self.fresh('exp'),
                np.asarray(p['y'], np.dtype(eqn.invars[0].aval.dtype)))
            name = self.node('Pow', [ins[0], e])
        elif prim == 'select_n':
            if len(ins) != 3:
                raise NotImplementedError('select_n with >2 cases')
            # select_n(pred, on_false, on_true); Where(cond, X=true, Y=false)
            name = self.node('Where', [ins[0], ins[2], ins[1]])
        elif prim in _REDUCE:
            if prim == 'reduce_sum':
                # ReduceSum takes axes as an input from opset 13
                axes = self.const_i64(p['axes'], 'axes')
                name = self.node('ReduceSum', [ins[0], axes], keepdims=0)
            else:
                # Max/Min/Prod keep axes as an attribute until opset 18
                name = self.node(_REDUCE[prim], ins, keepdims=0,
                                 axes=[int(a) for a in p['axes']])
        elif prim == 'argmax' or prim == 'argmin':
            # ONNX Arg* always yields int64; cast back to the traced dtype
            raw = self.node('ArgMax' if prim == 'argmax' else 'ArgMin',
                            ins, axis=int(p['axes'][0]), keepdims=0)
            name = self.node('Cast', [raw],
                             to=self._elem_type(out.aval.dtype))
        elif prim == 'reshape':
            tgt = self.const_i64(p['new_sizes'])
            name = self.node('Reshape', [ins[0], tgt])
        elif prim == 'squeeze':
            tgt = self.const_i64(out.aval.shape)
            name = self.node('Reshape', [ins[0], tgt])
        elif prim == 'transpose':
            name = self.node('Transpose', ins,
                             perm=[int(x) for x in p['permutation']])
        elif prim == 'broadcast_in_dim':
            name = self._broadcast_in_dim(ins[0], eqn)
        elif prim == 'concatenate':
            name = self.node('Concat', ins, axis=int(p['dimension']))
        elif prim == 'slice':
            starts = self.const_i64(p['start_indices'], 'starts')
            ends = self.const_i64(p['limit_indices'], 'ends')
            axes = self.const_i64(range(len(p['start_indices'])), 'axes')
            extra = []
            if p.get('strides'):
                extra = [self.const_i64(p['strides'], 'steps')]
            name = self.node('Slice', [ins[0], starts, ends, axes] + extra)
        elif prim == 'convert_element_type':
            name = self.node('Cast', ins,
                             to=self._elem_type(p['new_dtype']))
        elif prim == 'dot_general':
            name = self._dot_general(ins, eqn)
        elif prim == 'conv_general_dilated':
            name = self._conv(ins, eqn)
        elif prim == 'iota':
            arr = np.reshape(
                np.broadcast_to(
                    np.arange(out.aval.shape[p['dimension']],
                              dtype=np.dtype(p['dtype'])).reshape(
                        [-1 if i == p['dimension'] else 1
                         for i in range(len(out.aval.shape))]),
                    out.aval.shape), out.aval.shape)
            name = self.add_initializer(self.fresh('iota'), arr)
        elif prim in ('stop_gradient', 'copy'):
            name = self.node('Identity', ins)
        elif prim == 'exp2':
            two = self.add_initializer(
                self.fresh('two'),
                np.asarray(2.0, np.dtype(eqn.invars[0].aval.dtype)))
            name = self.node('Pow', [two, ins[0]])
        elif prim == 'log1p':
            one = self.add_initializer(
                self.fresh('one'),
                np.asarray(1.0, np.dtype(eqn.invars[0].aval.dtype)))
            name = self.node('Log', [self.node('Add', [ins[0], one])])
        elif prim == 'is_finite':
            inf = self.node('IsInf', ins)
            nan = self.node('IsNaN', ins)
            bad = self.node('Or', [inf, nan])
            name = self.node('Not', [bad])
        else:
            raise NotImplementedError(
                f'paddle.onnx.export: lax primitive `{prim}` has no ONNX '
                f'mapping; export this submodule with paddle.jit.save '
                f'(StableHLO) instead')
        self.set_name(out, name)

    def _broadcast_in_dim(self, in_name, eqn):
        p = eqn.params
        out_shape = [int(s) for s in p['shape']]
        bdims = list(p['broadcast_dimensions'])
        # 1) reshape to out rank with 1s, source dims placed at bdims
        interim = [1] * len(out_shape)
        for src_i, dst in enumerate(bdims):
            interim[dst] = int(eqn.invars[0].aval.shape[src_i])
        r = self.node('Reshape', [in_name, self.const_i64(interim)])
        # 2) expand to the target shape
        return self.node('Expand', [r, self.const_i64(out_shape)])

    def _dot_general(self, ins, eqn):
        """Lower any dot_general via Einsum (opset 12+)."""
        (lc, rc), (lb, rb) = eqn.params['dimension_numbers']
        lhs_rank = len(eqn.invars[0].aval.shape)
        rhs_rank = len(eqn.invars[1].aval.shape)
        letters = iter(string.ascii_lowercase)
        lhs_l = [None] * lhs_rank
        rhs_l = [None] * rhs_rank
        for li, ri in zip(lb, rb):
            lhs_l[li] = rhs_l[ri] = next(letters)
        for li, ri in zip(lc, rc):
            lhs_l[li] = rhs_l[ri] = next(letters)
        for i in range(lhs_rank):
            if lhs_l[i] is None:
                lhs_l[i] = next(letters)
        for i in range(rhs_rank):
            if rhs_l[i] is None:
                rhs_l[i] = next(letters)
        out_l = ([lhs_l[i] for i in lb]
                 + [lhs_l[i] for i in range(lhs_rank)
                    if i not in lb and i not in lc]
                 + [rhs_l[i] for i in range(rhs_rank)
                    if i not in rb and i not in rc])
        eqn_str = f"{''.join(lhs_l)},{''.join(rhs_l)}->{''.join(out_l)}"
        return self.node('Einsum', ins, equation=eqn_str)

    def _conv(self, ins, eqn):
        p = eqn.params
        dn = p['dimension_numbers']
        lhs_spec, rhs_spec, out_spec = dn
        nd = len(p['window_strides'])
        if (tuple(lhs_spec) != tuple(range(nd + 2))
                or tuple(out_spec) != tuple(range(nd + 2))
                or tuple(rhs_spec) != tuple(range(nd + 2))):
            raise NotImplementedError(
                'paddle.onnx.export: only NCHW/OIHW convolutions')
        if any(int(d) != 1 for d in p['lhs_dilation']):
            raise NotImplementedError(
                'paddle.onnx.export: transposed/fractionally-strided '
                'convolution (lhs_dilation > 1) is not mapped; use '
                'paddle.jit.save (StableHLO) for this layer')
        pads_lo = [int(a) for a, _ in p['padding']]
        pads_hi = [int(b) for _, b in p['padding']]
        return self.node(
            'Conv', ins,
            strides=[int(s) for s in p['window_strides']],
            dilations=[int(d) for d in p['rhs_dilation']],
            pads=pads_lo + pads_hi,
            group=int(p['feature_group_count']))

    # -- assembly -----------------------------------------------------------
    def finish(self, output_names, outvars, opset_version):
        outputs = [
            self.api.helper.make_tensor_value_info(
                n, self._elem_type(v.aval.dtype),
                [int(s) for s in v.aval.shape])
            for n, v in zip(output_names, outvars)]
        graph = self.api.helper.make_graph(
            self.nodes, 'paddle_tpu', self.inputs, outputs,
            initializer=self.initializers)
        return self.api.helper.make_model(
            graph, opset_imports=[
                self.api.helper.make_opsetid('', opset_version)])
