"""paddle.onnx (upstream: python/paddle/onnx/export.py, which delegates
to paddle2onnx).

The `onnx` package is not in this image, so `export` is an explicit
gate: when onnx is importable it writes a real ONNX ModelProto traced
from the layer's eval forward; otherwise it raises with a pointer to
`paddle.jit.save`, whose serialized-StableHLO artifact is this
framework's portable inference format (loadable on cpu/tpu without the
model class).
"""
from __future__ import annotations

__all__ = ['export']


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            'paddle.onnx.export requires the `onnx` package, which is not '
            'available in this offline build. Use paddle.jit.save(layer, '
            'path, input_spec) instead: it writes a self-contained '
            'StableHLO + params artifact that paddle.jit.load runs on '
            'cpu/tpu without the original model class.') from e
    raise NotImplementedError(
        'onnx is importable but the paddle_tpu ONNX converter is not '
        'implemented; use paddle.jit.save (StableHLO) for portable export.')
