"""paddle_tpu.nlp — transformer model zoo + generation + tokenizers.

Upstream analogue: PaddleNLP `paddlenlp.transformers`. The `transformers`
submodule alias mirrors the reference's import path
(`from paddlenlp.transformers import LlamaForCausalLM` →
`from paddle_tpu.nlp.transformers import LlamaForCausalLM`).
"""
from __future__ import annotations

from .bert import (BertConfig, BertForMaskedLM,
                   BertForSequenceClassification, BertModel)
from .ernie import (ErnieConfig, ErnieForMaskedLM,
                    ErnieForSequenceClassification, ErnieModel)
from .generation import GenerationMixin, Seq2SeqGenerationMixin
from .gpt import GPTConfig, GPTForCausalLM, GPTModel
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel)
from .t5 import T5Config, T5ForConditionalGeneration, T5Model
from .tokenizer import (BPETokenizer, PretrainedTokenizer,
                        WhitespaceTokenizer)

from . import transformers  # noqa: E402  (API-parity alias module)

__all__ = [
    'BertConfig', 'BertForMaskedLM', 'BertForSequenceClassification',
    'BertModel', 'ErnieConfig', 'ErnieForMaskedLM',
    'ErnieForSequenceClassification', 'ErnieModel', 'GenerationMixin',
    'GPTConfig', 'GPTForCausalLM', 'GPTModel', 'LlamaConfig',
    'LlamaForCausalLM', 'LlamaModel', 'Seq2SeqGenerationMixin',
    'T5Config', 'T5ForConditionalGeneration', 'T5Model', 'BPETokenizer',
    'PretrainedTokenizer', 'WhitespaceTokenizer', 'transformers',
]
