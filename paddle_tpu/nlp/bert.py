"""BERT model family (upstream analogue: PaddleNLP
`paddlenlp/transformers/bert/modeling.py` — BertModel, BertForMaskedLM,
BertForSequenceClassification).

TPU-native: the encoder stack reuses `nn.TransformerEncoder`-style
pre/post-LN blocks built on the shared fused-attention choke-point; all
shapes static so one jit covers the whole classification fine-tune step.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.common_layers import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer
from ..tensor import Tensor, apply_op, to_jax


class BertConfig:
    model_type = 'bert'

    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act='gelu',
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 pad_token_id=0, pool_act='tanh', num_labels=2,
                 use_recompute=False, **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.pad_token_id = pad_token_id
        self.pool_act = pool_act
        self.num_labels = num_labels
        self.use_recompute = use_recompute
        for k, v in kwargs.items():
            setattr(self, k, v)

    @classmethod
    def bert_base(cls, **kw):
        return cls(**kw)

    @classmethod
    def bert_large(cls, **kw):
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault('vocab_size', 128)
        kw.setdefault('hidden_size', 64)
        kw.setdefault('num_hidden_layers', 2)
        kw.setdefault('num_attention_heads', 4)
        kw.setdefault('intermediate_size', 128)
        kw.setdefault('max_position_embeddings', 128)
        kw.setdefault('hidden_dropout_prob', 0.0)
        kw.setdefault('attention_probs_dropout_prob', 0.0)
        return cls(**kw)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                extra_embeds=None):
        if position_ids is None:
            position_ids = apply_op(
                lambda iv: jnp.arange(iv.shape[1], dtype=jnp.int32),
                input_ids, _name='positions')
        if token_type_ids is None:
            token_type_ids = apply_op(
                lambda iv: jnp.zeros(iv.shape, jnp.int32), input_ids,
                _name='zeros_like')
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if extra_embeds is not None:
            h = h + extra_embeds
        return self.dropout(self.layer_norm(h))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)
        self.activation = F.tanh if config.pool_act == 'tanh' else F.relu

    def forward(self, hidden):
        first = apply_op(lambda h: h[:, 0], hidden, _name='cls_token')
        return self.activation(self.dense(first))


class BertModel(Layer):
    config_class = BertConfig
    base_model_prefix = 'bert'

    def __init__(self, config: BertConfig, add_pooling_layer=True):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            normalize_before=False, layer_norm_eps=config.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer,
                                          config.num_hidden_layers)
        self.pooler = BertPooler(config) if add_pooling_layer else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, extra_embeds=None, blocks_fn=None):
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(to_jax(input_ids))
        h = self.embeddings(ids, token_type_ids, position_ids,
                            extra_embeds=extra_embeds)
        if blocks_fn is not None:
            # pipeline-parallel path (fleet.DistTrainStep pp): the encoder
            # stack is replaced by a scheduled collective program; the
            # embeddings and pooler stay outside the pipelined region.
            if attention_mask is not None:
                raise ValueError('blocks_fn (pipeline) path supports only '
                                 'unpadded full-length batches '
                                 '(attention_mask unsupported)')
            h = apply_op(blocks_fn, h, _name='pp_blocks')
            pooled = self.pooler(h) if self.pooler is not None else None
            return (h, pooled) if pooled is not None else h
        mask = attention_mask
        if mask is not None and not isinstance(mask, Tensor):
            mask = Tensor(to_jax(mask))
        if mask is not None and len(mask.shape) == 2:
            mask = apply_op(lambda m: (m > 0)[:, None, None, :], mask,
                            _name='pad_mask')
        from .. import autograd as _ag
        if self.config.use_recompute and _ag._state.functional:
            # trade FLOPs for HBM exactly like LlamaModel (llama.py remat
            # branch): rematerialize each encoder block in backward
            import jax
            for layer in self.encoder.layers:
                h = Tensor(jax.checkpoint(
                    lambda hv, l=layer, m=mask: l(Tensor(hv),
                                                  src_mask=m).value)(h.value))
        else:
            h = self.encoder(h, src_mask=mask)
        pooled = self.pooler(h) if self.pooler is not None else None
        return (h, pooled) if pooled is not None else h


class BertForMaskedLM(Layer):
    config_class = BertConfig

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config, add_pooling_layer=False)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        epsilon=config.layer_norm_eps)
        self.decoder = Linear(config.hidden_size, config.vocab_size)

    def pp_blocks(self):
        """Pipeline-parallel protocol (consumed by fleet.DistTrainStep) —
        see LlamaForCausalLM.pp_blocks."""
        return 'bert.encoder.layers', list(self.bert.encoder.layers)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None, blocks_fn=None):
        h = self.bert(input_ids, token_type_ids=token_type_ids,
                      attention_mask=attention_mask, blocks_fn=blocks_fn)
        h = self.transform_norm(F.gelu(self.transform(h)))
        logits = self.decoder(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                (labels if isinstance(labels, Tensor)
                 else Tensor(to_jax(labels))).reshape([-1]),
                ignore_index=-100)
            return loss, logits
        return logits


class BertForSequenceClassification(Layer):
    config_class = BertConfig

    def __init__(self, config: BertConfig, num_classes=None):
        super().__init__()
        self.config = config
        self.num_classes = num_classes or config.num_labels
        self.bert = BertModel(config, add_pooling_layer=True)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, self.num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids=token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(
                logits,
                labels if isinstance(labels, Tensor)
                else Tensor(to_jax(labels)))
            return loss, logits
        return logits
