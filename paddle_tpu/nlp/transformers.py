"""Import-path parity with the reference's `paddlenlp.transformers`."""
from .bert import (BertConfig, BertForMaskedLM,  # noqa: F401
                   BertForSequenceClassification, BertModel)
from .ernie import (ErnieConfig, ErnieForMaskedLM,  # noqa: F401
                    ErnieForSequenceClassification, ErnieModel)
from .generation import GenerationMixin  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .t5 import (T5Config, T5ForConditionalGeneration,  # noqa: F401
                 T5Model)
from .tokenizer import (BPETokenizer, PretrainedTokenizer,  # noqa: F401
                        WhitespaceTokenizer)
