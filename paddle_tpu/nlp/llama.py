"""Llama model family (RMSNorm + SwiGLU + RoPE + GQA).

Upstream analogue: PaddleNLP `paddlenlp/transformers/llama/modeling.py`
(LlamaModel / LlamaForCausalLM). TPU-native design notes:
- attention lowers to `F.scaled_dot_product_attention` (pallas flash
  kernel on TPU, fused XLA softmax chain elsewhere); GQA is expressed by
  keeping K/V at `num_key_value_heads` and letting the attention core
  broadcast groups — no materialised `repeat` in the model code.
- decode uses a static-shape KV cache `[B, L_total, H_kv, D]` updated
  with `lax.dynamic_update_slice` so generation never recompiles.
- everything routes through `apply_op`, so the same forward works on the
  eager tape (training/backward) and traced under `jax.jit`.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.common_layers import Linear
from ..nn.layer import Layer
from ..nn.norm import RMSNorm
from ..nn.common_layers import Embedding
from ..tensor import Tensor, apply_op, to_jax
from .generation import (GenerationMixin, as_offset as _as_offset,
                         decode_mask as _decode_mask,
                         offset_grid as _offset_grid,
                         update_kv_cache as _update_kv_cache)


class LlamaConfig:
    model_type = 'llama'

    def __init__(self, vocab_size=32000, hidden_size=4096,
                 intermediate_size=11008, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=None,
                 max_position_embeddings=4096, rms_norm_eps=1e-6,
                 rope_theta=10000.0, tie_word_embeddings=False,
                 pad_token_id=0, bos_token_id=1, eos_token_id=2,
                 use_recompute=False, tensor_parallel=False,
                 sequence_parallel=False, **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.tie_word_embeddings = tie_word_embeddings
        self.pad_token_id = pad_token_id
        self.bos_token_id = bos_token_id
        self.eos_token_id = eos_token_id
        self.use_recompute = use_recompute
        self.tensor_parallel = tensor_parallel
        self.sequence_parallel = sequence_parallel
        for k, v in kwargs.items():
            setattr(self, k, v)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama2_7b(cls, **kw):
        return cls(vocab_size=32000, hidden_size=4096,
                   intermediate_size=11008, num_hidden_layers=32,
                   num_attention_heads=32, num_key_value_heads=32,
                   max_position_embeddings=4096, **kw)

    @classmethod
    def llama2_13b(cls, **kw):
        return cls(vocab_size=32000, hidden_size=5120,
                   intermediate_size=13824, num_hidden_layers=40,
                   num_attention_heads=40, num_key_value_heads=40, **kw)

    @classmethod
    def llama2_70b(cls, **kw):
        return cls(vocab_size=32000, hidden_size=8192,
                   intermediate_size=28672, num_hidden_layers=80,
                   num_attention_heads=64, num_key_value_heads=8, **kw)

    @classmethod
    def tiny(cls, **kw):
        """Test-sized config (also used by CI smoke tests)."""
        kw.setdefault('vocab_size', 128)
        kw.setdefault('hidden_size', 64)
        kw.setdefault('intermediate_size', 128)
        kw.setdefault('num_hidden_layers', 2)
        kw.setdefault('num_attention_heads', 4)
        kw.setdefault('num_key_value_heads', 2)
        kw.setdefault('max_position_embeddings', 256)
        return cls(**kw)


def _rope(x, positions, theta):
    """Rotary embedding, rotate-half convention. x: [B, S, H, D] raw array,
    positions: [S] or [B, S] raw int array."""
    d = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos = positions.astype(jnp.float32)
    freqs = pos[..., None] * inv                      # [..., S, D/2]
    while freqs.ndim < 3:
        freqs = freqs[None]                           # [B(1), S, D/2]
    cos = jnp.cos(freqs)[:, :, None, :]               # [B, S, 1, D/2]
    sin = jnp.sin(freqs)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def _col_linear(config, in_f, out_f):
    """Plain Linear, or mp-column-sharded when config.tensor_parallel
    (upstream: tensor_parallel_degree>1 swaps in fleet's parallel layers)."""
    if config.tensor_parallel:
        from ..distributed.parallel_layers import ColumnParallelLinear
        return ColumnParallelLinear(in_f, out_f, has_bias=False,
                                    gather_output=False)
    return Linear(in_f, out_f, bias_attr=False)


def _row_linear(config, in_f, out_f):
    if config.tensor_parallel:
        from ..distributed.parallel_layers import RowParallelLinear
        return RowParallelLinear(in_f, out_f, has_bias=False,
                                 input_is_parallel=True)
    return Linear(in_f, out_f, bias_attr=False)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h, hd = config.hidden_size, config.head_dim
        self.num_heads = config.num_attention_heads
        self.num_key_value_heads = config.num_key_value_heads
        self.head_dim = hd
        self.q_proj = _col_linear(config, h, self.num_heads * hd)
        self.k_proj = _col_linear(config, h,
                                  self.num_key_value_heads * hd)
        self.v_proj = _col_linear(config, h,
                                  self.num_key_value_heads * hd)
        self.o_proj = _row_linear(config, self.num_heads * hd, h)

    def forward(self, hidden, position_offset=None, attn_mask=None,
                cache=None, cache_offset=None):
        cfg = self.config
        offset = _as_offset(position_offset)
        # cache_offset = SLOT index in the static cache (scalar, or [B]
        # per-row slots for the serving engine's slot pool);
        # position_offset = LOGICAL position for RoPE (scalar or [B] for
        # left-padded prompts). They coincide for unpadded prompts.
        slot = _as_offset(cache_offset) if cache_offset is not None \
            else offset
        nh, nkv, hd = self.num_heads, self.num_key_value_heads, self.head_dim
        theta = cfg.rope_theta

        q = apply_op(
            lambda v: v.reshape(v.shape[0], v.shape[1], nh, hd),
            self.q_proj(hidden), _name='split_heads')
        k = apply_op(
            lambda v: v.reshape(v.shape[0], v.shape[1], nkv, hd),
            self.k_proj(hidden), _name='split_heads')
        v = apply_op(
            lambda v_: v_.reshape(v_.shape[0], v_.shape[1], nkv, hd),
            self.v_proj(hidden), _name='split_heads')

        # offset rides as an op INPUT (int tensor), not a closure capture:
        # a captured jax scalar would make every rope call uncacheable in
        # the eager dispatch cache
        def rope_q(qv, off):
            pos = _offset_grid(off, qv.shape[1])
            return _rope(qv, pos, theta)
        off_t = offset if isinstance(offset, Tensor) else Tensor(offset)
        q = apply_op(rope_q, q, off_t, _name='rope')
        k = apply_op(rope_q, k, off_t, _name='rope')

        if cache is None:
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                                 is_causal=True)
        else:
            k_cache, v_cache = _update_kv_cache(cache[0], cache[1], k, v,
                                                slot)
            # a caller-built mask (padded-prompt decode) wins over the
            # default slot-causal one
            mask = attn_mask if attn_mask is not None \
                else _decode_mask(q, k_cache, slot)
            out = F.scaled_dot_product_attention(q, k_cache, v_cache,
                                                 attn_mask=mask)
        out = apply_op(
            lambda t: t.reshape(t.shape[0], t.shape[1], nh * hd),
            out, _name='merge_heads')
        out = self.o_proj(out)
        if cache is not None:
            return out, (k_cache, v_cache)
        return out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = _col_linear(config, h, i)
        self.up_proj = _col_linear(config, h, i)
        self.down_proj = _row_linear(config, i, h)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)

    def forward(self, hidden, position_offset=None, attn_mask=None,
                cache=None, cache_offset=None):
        residual = hidden
        h = self.input_layernorm(hidden)
        attn_out = self.self_attn(h, position_offset=position_offset,
                                  attn_mask=attn_mask, cache=cache,
                                  cache_offset=cache_offset)
        new_cache = None
        if cache is not None:
            attn_out, new_cache = attn_out
        h = residual + attn_out
        h = h + self.mlp(self.post_attention_layernorm(h))
        if cache is not None:
            return h, new_cache
        return h


class LlamaPretrainedModel(Layer):
    config_class = LlamaConfig
    base_model_prefix = 'llama'


class LlamaModel(LlamaPretrainedModel):
    """Reference parity: paddlenlp LlamaModel (embed → N decoder layers →
    final RMSNorm)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            from ..distributed.parallel_layers import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
        else:
            self.embed_tokens = Embedding(config.vocab_size,
                                          config.hidden_size)
        self.layers = [LlamaDecoderLayer(config)
                       for _ in range(config.num_hidden_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f'layers.{i}', l)
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_offset=None, attention_mask=None,
                cache=None, use_cache=False, blocks_fn=None,
                cache_offset=None):
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(to_jax(input_ids))
        h = self.embed_tokens(ids)
        if blocks_fn is not None:
            # pipeline-parallel path (fleet.DistTrainStep pp): the decoder
            # stack is replaced by a scheduled collective program; embed
            # and final norm stay outside the pipelined region.
            if attention_mask is not None or cache is not None \
                    or position_offset is not None:
                raise ValueError('blocks_fn (pipeline) path supports only '
                                 'full-length causal batches from position '
                                 '0 (mask/cache/offset unsupported)')
            h = apply_op(blocks_fn, h, _name='pp_blocks')
            return self.norm(h)
        sp_pin = None
        if self.config.sequence_parallel:
            # keep activations sequence-sharded over 'sp' between blocks;
            # GSPMD gathers seq only where attention truly needs it
            from ..distributed.parallel_layers import _constraint
            from jax.sharding import PartitionSpec as P
            sp_pin = _constraint(P('dp', 'sp', None))
            h = sp_pin(h)
        mask = attention_mask
        if mask is not None and not isinstance(mask, Tensor):
            mask = Tensor(to_jax(mask))
        if mask is not None and len(mask.shape) == 2:
            # [B, S] padding mask -> [B, 1, 1, S] boolean
            mask = apply_op(
                lambda m: (m > 0)[:, None, None, :], mask, _name='pad_mask')
        from .. import autograd as _ag
        remat = (self.config.use_recompute and cache is None
                 and _ag._state.functional)
        new_caches = []
        for i, layer in enumerate(self.layers):
            layer_cache = None
            if cache is not None:
                kc, vc = cache[i]
                layer_cache = (
                    kc if isinstance(kc, Tensor) else Tensor(kc),
                    vc if isinstance(vc, Tensor) else Tensor(vc))
            if remat:
                # trade FLOPs for HBM: rematerialize the block in backward
                # (upstream: recompute_configs; here jax.checkpoint —
                # closed-over traced params are lifted and differentiated).
                # use_recompute='dots' keeps matmul outputs and recomputes
                # only elementwise chains; 'dots_no_batch' keeps only
                # weight-matmul outputs (batched attention dots at
                # b*h*s^2 would blow HBM) — the middle trade: backward
                # re-runs just attention + elementwise, so the remat
                # overhead drops from ~1/3 of model flops to a few %.
                policy = {
                    'dots': jax.checkpoint_policies.dots_saveable,
                    'dots_no_batch':
                        jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable,
                }.get(self.config.use_recompute)
                out = Tensor(jax.checkpoint(
                    lambda hv, l=layer: l(
                        Tensor(hv), position_offset=position_offset,
                        attn_mask=mask).value, policy=policy)(h.value))
            else:
                out = layer(h, position_offset=position_offset,
                            attn_mask=mask, cache=layer_cache,
                            cache_offset=cache_offset)
            if layer_cache is not None:
                h, c = out
                new_caches.append(c)
            else:
                h = out
            if sp_pin is not None:
                h = sp_pin(h)
        h = self.norm(h)
        if use_cache:
            return h, tuple(new_caches)
        return h

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        dt = dtype or 'float32'
        shape = (batch_size, int(max_length), cfg.num_key_value_heads,
                 cfg.head_dim)
        return tuple(
            (jnp.zeros(shape, dt), jnp.zeros(shape, dt))
            for _ in range(cfg.num_hidden_layers))


class LlamaForCausalLM(LlamaPretrainedModel, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, h):
        if self.lm_head is not None:
            return self.lm_head(h)
        w = self.llama.embed_tokens.weight
        return apply_op(lambda hv, wv: hv @ wv.T, h, w, _name='tied_lm_head')

    def pp_blocks(self):
        """Pipeline-parallel protocol (consumed by fleet.DistTrainStep):
        (param-name prefix of the uniform decoder blocks, the block list).
        """
        return 'llama.layers', self.llama.layers

    def forward(self, input_ids, position_offset=None, attention_mask=None,
                cache=None, use_cache=False, labels=None, blocks_fn=None,
                cache_offset=None):
        out = self.llama(input_ids, position_offset=position_offset,
                         attention_mask=attention_mask, cache=cache,
                         use_cache=use_cache, blocks_fn=blocks_fn,
                         cache_offset=cache_offset)
        if use_cache:
            h, new_cache = out
        else:
            h, new_cache = out, None
        logits = self._logits(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                (labels if isinstance(labels, Tensor)
                 else Tensor(to_jax(labels))).reshape([-1]))
            return (loss, logits, new_cache) if use_cache else (loss, logits)
        if use_cache:
            return logits, new_cache
        return logits

    def init_cache(self, batch_size, max_length, dtype=None):
        return self.llama.init_cache(batch_size, max_length, dtype)
