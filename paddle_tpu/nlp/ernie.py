"""ERNIE model family (upstream analogue: PaddleNLP
`paddlenlp/transformers/ernie/modeling.py`).

Architecturally a BERT-style encoder plus task-type embeddings; shares
the TPU-native encoder stack with bert.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.common_layers import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..tensor import Tensor, apply_op, to_jax
from .bert import BertConfig, BertModel


class ErnieConfig(BertConfig):
    model_type = 'ernie'

    def __init__(self, task_type_vocab_size=3, use_task_id=True, **kwargs):
        super().__init__(**kwargs)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id


class ErnieModel(Layer):
    config_class = ErnieConfig
    base_model_prefix = 'ernie'

    def __init__(self, config: ErnieConfig, add_pooling_layer=True):
        super().__init__()
        self.config = config
        self.bert = BertModel(config, add_pooling_layer=add_pooling_layer)
        if config.use_task_id:
            self.task_type_embeddings = Embedding(
                config.task_type_vocab_size, config.hidden_size)
        else:
            self.task_type_embeddings = None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None, blocks_fn=None):
        task_emb = None
        if self.task_type_embeddings is not None:
            ids = input_ids if isinstance(input_ids, Tensor) \
                else Tensor(to_jax(input_ids))
            if task_type_ids is None:
                task_type_ids = apply_op(
                    lambda iv: jnp.zeros(iv.shape, jnp.int32), ids,
                    _name='zeros_like')
            task_emb = self.task_type_embeddings(task_type_ids)
        return self.bert(input_ids, token_type_ids=token_type_ids,
                         position_ids=position_ids,
                         attention_mask=attention_mask,
                         extra_embeds=task_emb, blocks_fn=blocks_fn)


class ErnieForMaskedLM(Layer):
    config_class = ErnieConfig

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.ernie = ErnieModel(config, add_pooling_layer=False)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        epsilon=config.layer_norm_eps)
        self.decoder = Linear(config.hidden_size, config.vocab_size)

    def pp_blocks(self):
        """Pipeline-parallel protocol (consumed by fleet.DistTrainStep) —
        see LlamaForCausalLM.pp_blocks. Covers BASELINE config #5 (ERNIE
        with pipeline-parallel + recompute; upstream
        fleet/meta_parallel/pipeline_parallel.py + recompute/)."""
        return 'ernie.bert.encoder.layers', \
            list(self.ernie.bert.encoder.layers)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None, labels=None, blocks_fn=None):
        h = self.ernie(input_ids, token_type_ids=token_type_ids,
                       attention_mask=attention_mask,
                       task_type_ids=task_type_ids, blocks_fn=blocks_fn)
        h = self.transform_norm(F.gelu(self.transform(h)))
        logits = self.decoder(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                (labels if isinstance(labels, Tensor)
                 else Tensor(to_jax(labels))).reshape([-1]),
                ignore_index=-100)
            return loss, logits
        return logits


class ErnieForSequenceClassification(Layer):
    config_class = ErnieConfig

    def __init__(self, config: ErnieConfig, num_classes=None):
        super().__init__()
        self.config = config
        self.num_classes = num_classes or config.num_labels
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, self.num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids=token_type_ids,
                               attention_mask=attention_mask,
                               task_type_ids=task_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(
                logits, labels if isinstance(labels, Tensor)
                else Tensor(to_jax(labels)))
            return loss, logits
        return logits
