"""C++-accelerated BPE tokenizer (upstream analogue: PaddleNLP
faster_tokenizer / paddlenlp_ops fast tokenizers).

`FastBPETokenizer` is a drop-in `BPETokenizer` whose `tokenize`/`encode`
hot path (greedy merge loop) runs in csrc/fast_tokenizer.cpp via ctypes
— no Python interpreter cost per merge. Falls back to the pure-python
path transparently when no compiler is available.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

from ..analysis.runtime import concurrency as _concurrency
from .tokenizer import BPETokenizer, _WORD_END

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), 'csrc')
_BUILD = os.path.join(_CSRC, 'build')
_LIB_PATH = os.path.join(_BUILD, 'libpaddle_tpu_fast_tokenizer.so')
_SRC = os.path.join(_CSRC, 'fast_tokenizer.cpp')

_lock = _concurrency.Lock('fast_tokenizer._lock')
_lib = None
_tried = False


def _build():
    os.makedirs(_BUILD, exist_ok=True)
    tmp = _LIB_PATH + '.tmp.so'
    subprocess.run(
        ['g++', '-O3', '-fPIC', '-shared', '-std=c++17', _SRC, '-o', tmp],
        check=True, capture_output=True)
    os.replace(tmp, _LIB_PATH)


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def _bind(lib):
    lib.bpe_create.restype = ctypes.c_void_p
    lib.bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.bpe_set_unk.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.bpe_add_token.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
    lib.bpe_add_merge.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_int]
    lib.bpe_encode.restype = ctypes.c_int
    lib.bpe_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int32,
                               ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    return lib


def get_lib():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _stale():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception:  # paddle-lint: disable=swallowed-exception -- optional native lib gate; absence is a supported config surfaced via available()
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


class FastBPETokenizer(BPETokenizer):
    """BPETokenizer with the merge loop in C++. The python data model
    (vocab dict, merges list, save/load) is unchanged; only encode's hot
    path moves to native code."""

    def __init__(self, vocab=None, merges=None):
        super().__init__(vocab, merges)
        self._native = None
        self._native_dirty = True

    # any mutation of vocab/merges (training, load) re-syncs the C++ side
    def _load_extra_state(self, state):
        super()._load_extra_state(state)
        # from_pretrained builds via __new__ (no __init__): create the
        # native-handle slots here as well
        self._native = getattr(self, '_native', None)
        self._native_dirty = True

    def train_from_iterator(self, it, vocab_size=1000, min_frequency=2):
        out = super().train_from_iterator(it, vocab_size, min_frequency)
        self._native_dirty = True
        return out

    def _sync_native(self):
        lib = get_lib()
        if lib is None:
            return None
        if self._native is not None and not self._native_dirty:
            return self._native
        if self._native is not None:
            lib.bpe_destroy(self._native)
        h = lib.bpe_create()
        lib.bpe_set_unk(h, self.unk_token_id)
        for tok, i in self.vocab.items():
            lib.bpe_add_token(h, tok.encode('utf-8'), i)
        for rank, (a, b) in enumerate(self.merges):
            lib.bpe_add_merge(h, a.encode('utf-8'), b.encode('utf-8'), rank)
        self._native = h
        self._native_dirty = False
        return h

    def encode(self, text: str, add_special_tokens: bool = False,
               max_length: Optional[int] = None) -> List[int]:
        h = self._sync_native()
        if h is None:  # no compiler: python fallback
            return super().encode(text, add_special_tokens, max_length)
        lib = get_lib()
        # ' '.join(text.split()) reproduces python str.split() semantics
        # exactly (unicode whitespace separators) so the C side only ever
        # sees ASCII-space-separated words; words keep NUL bytes, which
        # the explicit-length API passes through un-truncated
        data = ' '.join(text.split()).encode('utf-8')
        cap = max(256, len(data) * 2)
        buf = (ctypes.c_int32 * cap)()
        n = lib.bpe_encode(h, data, len(data), buf, cap)
        if n > cap:  # pathological byte-fallback blowup: retry exact
            buf = (ctypes.c_int32 * n)()
            n = lib.bpe_encode(h, data, len(data), buf, n)
        ids = list(buf[:n])
        if add_special_tokens:
            ids = [self.bos_token_id] + ids + [self.eos_token_id]
        if max_length is not None:
            ids = ids[:max_length]
        return ids

    def tokenize(self, text: str) -> List[str]:
        h = self._sync_native()
        if h is None:
            return super().tokenize(text)
        return self.convert_ids_to_tokens(self.encode(text))

    def __del__(self):
        try:
            if self._native is not None and _lib is not None:
                _lib.bpe_destroy(self._native)
                self._native = None
        except Exception:  # paddle-lint: disable=swallowed-exception -- destructor path: interpreter/library may already be tearing down
            pass
