"""Offline tokenizers (upstream analogue: PaddleNLP
`paddlenlp/transformers/*/tokenizer.py` + fast tokenizers).

Two fully-offline implementations sharing one API surface:
- `WhitespaceTokenizer` — vocab over whitespace-split tokens.
- `BPETokenizer` — byte-level BPE-lite: trainable merges
  (`train_from_iterator`), greedy merge application, byte fallback so any
  string round-trips. Vocab/merges persist as JSON (`save_pretrained` /
  `from_pretrained` on a local directory; hub download is gated offline).
"""
from __future__ import annotations

import collections
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def _validate_tokenizer_state(fname: str, state) -> Dict:
    """Schema check for a saved tokenizer.json; raises ValueError naming
    the file and the first offending entry."""
    if not isinstance(state, dict):
        raise ValueError(
            f'{fname}: expected a JSON object, got {type(state).__name__}')
    vocab = state.get('vocab')
    if not isinstance(vocab, dict) or not vocab:
        raise ValueError(f"{fname}: 'vocab' must be a non-empty object "
                         f'mapping token -> id')
    for tok, idx in vocab.items():
        if not isinstance(idx, int) or isinstance(idx, bool) or idx < 0:
            raise ValueError(
                f'{fname}: vocab entry {tok!r} has invalid id {idx!r} '
                f'(want a non-negative integer)')
    ids = list(vocab.values())
    if len(set(ids)) != len(ids):
        dup = next(i for i in ids if ids.count(i) > 1)
        raise ValueError(f'{fname}: duplicate token id {dup} in vocab')
    merges = state.get('merges', [])
    if not isinstance(merges, list):
        raise ValueError(f"{fname}: 'merges' must be a list")
    for m in merges:
        if not (isinstance(m, (list, tuple)) and len(m) == 2
                and all(isinstance(s, str) for s in m)):
            raise ValueError(
                f'{fname}: merge entry {m!r} is not a [left, right] '
                f'string pair')
    return state


class PretrainedTokenizer:
    pad_token = '<pad>'
    unk_token = '<unk>'
    bos_token = '<s>'
    eos_token = '</s>'
    mask_token = '<mask>'

    def __init__(self, vocab: Optional[Dict[str, int]] = None):
        self.vocab: Dict[str, int] = dict(vocab or {})
        for tok in (self.pad_token, self.unk_token, self.bos_token,
                    self.eos_token, self.mask_token):
            if tok not in self.vocab:
                self.vocab[tok] = len(self.vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}

    # -- special ids --------------------------------------------------------
    @property
    def pad_token_id(self):
        return self.vocab[self.pad_token]

    @property
    def unk_token_id(self):
        return self.vocab[self.unk_token]

    @property
    def bos_token_id(self):
        return self.vocab[self.bos_token]

    @property
    def eos_token_id(self):
        return self.vocab[self.eos_token]

    @property
    def vocab_size(self):
        return len(self.vocab)

    def __len__(self):
        return len(self.vocab)

    # -- core API -----------------------------------------------------------
    def tokenize(self, text: str) -> List[str]:
        raise NotImplementedError

    def convert_tokens_to_ids(self, tokens):
        if isinstance(tokens, str):
            return self.vocab.get(tokens, self.unk_token_id)
        return [self.vocab.get(t, self.unk_token_id) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        if isinstance(ids, int):
            return self.inv_vocab.get(ids, self.unk_token)
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def encode(self, text: str, add_special_tokens: bool = False,
               max_length: Optional[int] = None) -> List[int]:
        ids = self.convert_tokens_to_ids(self.tokenize(text))
        if add_special_tokens:
            ids = [self.bos_token_id] + ids + [self.eos_token_id]
        if max_length is not None:
            ids = ids[:max_length]
        return ids

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        special = {self.pad_token_id, self.bos_token_id, self.eos_token_id,
                   self.vocab[self.mask_token]}
        toks = [self.inv_vocab.get(int(i), self.unk_token) for i in ids
                if not (skip_special_tokens and int(i) in special)]
        return self._detokenize(toks)

    def _detokenize(self, tokens: List[str]) -> str:
        return ' '.join(tokens)

    def __call__(self, text, padding: bool = False,
                 max_length: Optional[int] = None,
                 add_special_tokens: bool = False,
                 return_attention_mask: bool = True):
        texts = [text] if isinstance(text, str) else list(text)
        encoded = [self.encode(t, add_special_tokens=add_special_tokens,
                               max_length=max_length) for t in texts]
        if padding:
            width = max_length or max(len(e) for e in encoded)
            masks = [[1] * len(e) + [0] * (width - len(e)) for e in encoded]
            encoded = [e + [self.pad_token_id] * (width - len(e))
                       for e in encoded]
        else:
            masks = [[1] * len(e) for e in encoded]
        out = {'input_ids': encoded[0] if isinstance(text, str) else encoded}
        if return_attention_mask:
            out['attention_mask'] = (masks[0] if isinstance(text, str)
                                     else masks)
        return out

    # -- persistence --------------------------------------------------------
    def _extra_state(self) -> Dict:
        return {}

    def save_pretrained(self, save_dir: str):
        os.makedirs(save_dir, exist_ok=True)
        state = {'class': type(self).__name__, 'vocab': self.vocab}
        state.update(self._extra_state())
        with open(os.path.join(save_dir, 'tokenizer.json'), 'w') as f:
            json.dump(state, f)

    @classmethod
    def from_pretrained(cls, path: str):
        """Load from a local directory. Hub names are rejected offline
        (reference downloads from bos/huggingface; zero-egress here).
        The file schema is validated up front so a malformed directory
        fails with a clear message, not a KeyError mid-load."""
        fname = os.path.join(path, 'tokenizer.json')
        if not os.path.isfile(fname):
            raise OSError(
                f'{path!r} is not a local tokenizer directory (offline '
                f'build: hub downloads are disabled; call save_pretrained '
                f'first)')
        try:
            with open(fname) as f:
                state = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f'{fname}: not valid JSON: {e}') from e
        state = _validate_tokenizer_state(fname, state)
        klass = {c.__name__: c for c in
                 (WhitespaceTokenizer, BPETokenizer)}.get(
                     state.get('class'), cls)
        tok = klass.__new__(klass)
        PretrainedTokenizer.__init__(tok, state['vocab'])
        tok._load_extra_state(state)
        return tok

    def _load_extra_state(self, state: Dict):
        pass


class WhitespaceTokenizer(PretrainedTokenizer):
    def tokenize(self, text: str) -> List[str]:
        return text.strip().split()

    def train_from_iterator(self, it: Iterable[str],
                            vocab_size: Optional[int] = None):
        counts = collections.Counter()
        for line in it:
            counts.update(line.strip().split())
        for tok, _ in counts.most_common(vocab_size):
            if tok not in self.vocab:
                self.vocab[tok] = len(self.vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        return self


_WORD_END = '</w>'


class BPETokenizer(PretrainedTokenizer):
    """Byte-level-ish BPE: characters as base symbols plus byte fallback
    tokens `<0xNN>` so unseen characters still encode."""

    def __init__(self, vocab=None, merges: Optional[Sequence[Tuple[str, str]]] = None):
        super().__init__(vocab)
        self.merges: List[Tuple[str, str]] = [tuple(m) for m in (merges or [])]
        self._ranks = {m: i for i, m in enumerate(self.merges)}
        for i in range(256):
            bt = f'<0x{i:02X}>'
            if bt not in self.vocab:
                self.vocab[bt] = len(self.vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}

    def _extra_state(self):
        return {'merges': [list(m) for m in self.merges]}

    def _load_extra_state(self, state):
        self.merges = [tuple(m) for m in state.get('merges', [])]
        self._ranks = {m: i for i, m in enumerate(self.merges)}

    def _bpe_word(self, word: str) -> List[str]:
        symbols = list(word) + [_WORD_END]
        while len(symbols) > 1:
            pairs = [(self._ranks.get((a, b), 1 << 60), i)
                     for i, (a, b) in enumerate(zip(symbols, symbols[1:]))]
            rank, i = min(pairs)
            if rank >= 1 << 60:
                break
            symbols = symbols[:i] + [symbols[i] + symbols[i + 1]] \
                + symbols[i + 2:]
        return symbols

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in text.strip().split():
            for sym in self._bpe_word(word):
                if sym in self.vocab:
                    out.append(sym)
                else:  # byte fallback
                    for b in sym.encode('utf-8'):
                        out.append(f'<0x{b:02X}>')
        return out

    def _detokenize(self, tokens: List[str]) -> str:
        text, byte_buf = [], []

        def flush_bytes():
            if byte_buf:
                text.append(bytes(byte_buf).decode('utf-8', errors='replace'))
                byte_buf.clear()
        for t in tokens:
            if t.startswith('<0x') and t.endswith('>') and len(t) == 6:
                byte_buf.append(int(t[3:5], 16))
                continue
            flush_bytes()
            text.append(t)
        flush_bytes()
        return ''.join(text).replace(_WORD_END, ' ').strip()

    def train_from_iterator(self, it: Iterable[str], vocab_size: int = 1000,
                            min_frequency: int = 2):
        word_counts = collections.Counter()
        for line in it:
            word_counts.update(line.strip().split())
        words = {w: list(w) + [_WORD_END] for w in word_counts}
        # seed vocab with single characters
        for w in word_counts:
            for ch in w:
                if ch not in self.vocab:
                    self.vocab[ch] = len(self.vocab)
        if _WORD_END not in self.vocab:
            self.vocab[_WORD_END] = len(self.vocab)
        while len(self.vocab) < vocab_size:
            pair_counts = collections.Counter()
            for w, syms in words.items():
                c = word_counts[w]
                for pair in zip(syms, syms[1:]):
                    pair_counts[pair] += c
            if not pair_counts:
                break
            (a, b), cnt = pair_counts.most_common(1)[0]
            if cnt < min_frequency:
                break
            self.merges.append((a, b))
            merged = a + b
            if merged not in self.vocab:
                self.vocab[merged] = len(self.vocab)
            for w, syms in words.items():
                out, i = [], 0
                while i < len(syms):
                    if i + 1 < len(syms) and syms[i] == a and syms[i + 1] == b:
                        out.append(merged)
                        i += 2
                    else:
                        out.append(syms[i])
                        i += 1
                words[w] = out
        self._ranks = {m: i for i, m in enumerate(self.merges)}
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        return self
