"""GPT model family (upstream analogue: PaddleNLP
`paddlenlp/transformers/gpt/modeling.py` — GPTModel / GPTForCausalLM,
GPT-3 1.3B headline config).

TPU-native: pre-LN transformer with learned position embeddings; causal
attention lowers to the shared `F.scaled_dot_product_attention`
choke-point (pallas flash kernel on TPU); decode shares the static-shape
KV-cache scheme with the Llama family (see llama.py docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.common_layers import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..tensor import Tensor, apply_op, to_jax
from .generation import (GenerationMixin, as_offset as _as_offset,
                         decode_mask as _decode_mask,
                         offset_grid as _offset_grid,
                         update_kv_cache as _update_kv_cache)


class GPTConfig:
    model_type = 'gpt'

    def __init__(self, vocab_size=50304, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=None, hidden_act='gelu',
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=1024, initializer_range=0.02,
                 layer_norm_epsilon=1e-5, pad_token_id=0, eos_token_id=50256,
                 bos_token_id=50256, tie_word_embeddings=True, **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.initializer_range = initializer_range
        self.layer_norm_epsilon = layer_norm_epsilon
        self.pad_token_id = pad_token_id
        self.eos_token_id = eos_token_id
        self.bos_token_id = bos_token_id
        self.tie_word_embeddings = tie_word_embeddings
        for k, v in kwargs.items():
            setattr(self, k, v)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def gpt3_1p3b(cls, **kw):
        """GPT-3 XL (1.3B): 24 layers, d_model 2048, 16 heads x 128."""
        return cls(vocab_size=50304, hidden_size=2048, num_hidden_layers=24,
                   num_attention_heads=16, max_position_embeddings=2048, **kw)

    @classmethod
    def gpt2_small(cls, **kw):
        return cls(vocab_size=50304, hidden_size=768, num_hidden_layers=12,
                   num_attention_heads=12, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault('vocab_size', 128)
        kw.setdefault('hidden_size', 64)
        kw.setdefault('num_hidden_layers', 2)
        kw.setdefault('num_attention_heads', 4)
        kw.setdefault('max_position_embeddings', 128)
        kw.setdefault('hidden_dropout_prob', 0.0)
        kw.setdefault('attention_probs_dropout_prob', 0.0)
        return cls(**kw)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_attention_heads
        self.num_heads = nh
        self.head_dim = config.head_dim
        self.qkv_proj = Linear(h, 3 * h)
        self.out_proj = Linear(h, h)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, hidden, position_offset=None, attn_mask=None,
                cache=None, cache_offset=None):
        nh, hd = self.num_heads, self.head_dim
        offset = _as_offset(position_offset)
        slot = _as_offset(cache_offset) if cache_offset is not None \
            else offset
        qkv = self.qkv_proj(hidden)
        q, k, v = (apply_op(
            lambda t, i=i: t[..., i * nh * hd:(i + 1) * nh * hd].reshape(
                t.shape[0], t.shape[1], nh, hd),
            qkv, _name='split_qkv') for i in range(3))
        if cache is None:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=True,
                dropout_p=self.dropout_p, training=self.training)
        else:
            k_cache, v_cache = _update_kv_cache(cache[0], cache[1], k, v,
                                                slot)
            mask = attn_mask if attn_mask is not None \
                else _decode_mask(q, k_cache, slot)
            out = F.scaled_dot_product_attention(q, k_cache, v_cache,
                                                 attn_mask=mask)
        out = apply_op(lambda t: t.reshape(t.shape[0], t.shape[1], nh * hd),
                       out, _name='merge_heads')
        out = self.out_proj(out)
        if cache is not None:
            return out, (k_cache, v_cache)
        return out


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.norm1 = LayerNorm(config.hidden_size,
                               epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.norm2 = LayerNorm(config.hidden_size,
                               epsilon=config.layer_norm_epsilon)
        self.linear1 = Linear(config.hidden_size, config.intermediate_size)
        self.linear2 = Linear(config.intermediate_size, config.hidden_size)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.act = {'gelu': F.gelu, 'relu': F.relu}[config.hidden_act]

    def forward(self, hidden, position_offset=None, attn_mask=None,
                cache=None, cache_offset=None):
        residual = hidden
        out = self.attn(self.norm1(hidden), position_offset=position_offset,
                        attn_mask=attn_mask, cache=cache,
                        cache_offset=cache_offset)
        new_cache = None
        if cache is not None:
            out, new_cache = out
        h = residual + self.dropout(out)
        h = h + self.dropout(self.linear2(self.act(self.linear1(
            self.norm2(h)))))
        if cache is not None:
            return h, new_cache
        return h


class GPTModel(Layer):
    config_class = GPTConfig
    base_model_prefix = 'gpt'

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size)
        self.embed_dropout = Dropout(config.hidden_dropout_prob)
        self.layers = [GPTDecoderLayer(config)
                       for _ in range(config.num_hidden_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f'layers.{i}', l)
        self.final_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_offset=None, attention_mask=None,
                cache=None, use_cache=False, blocks_fn=None,
                cache_offset=None):
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(to_jax(input_ids))
        offset = _as_offset(position_offset)
        pos = apply_op(
            lambda iv: jnp.clip(_offset_grid(offset, iv.shape[1]), 0, None),
            ids, _name='positions')
        h = self.word_embeddings(ids) + self.position_embeddings(pos)
        h = self.embed_dropout(h)
        if blocks_fn is not None:
            # pipeline-parallel path — see LlamaModel.forward
            if attention_mask is not None or cache is not None:
                raise ValueError('blocks_fn (pipeline) path supports only '
                                 'full-length causal batches')
            h = apply_op(blocks_fn, h, _name='pp_blocks')
            return self.final_norm(h)
        mask = attention_mask
        if mask is not None and not isinstance(mask, Tensor):
            mask = Tensor(to_jax(mask))
        if mask is not None and len(mask.shape) == 2:
            mask = apply_op(lambda m: (m > 0)[:, None, None, :], mask,
                            _name='pad_mask')
        new_caches = []
        for i, layer in enumerate(self.layers):
            layer_cache = None
            if cache is not None:
                kc, vc = cache[i]
                layer_cache = (
                    kc if isinstance(kc, Tensor) else Tensor(kc),
                    vc if isinstance(vc, Tensor) else Tensor(vc))
            out = layer(h, position_offset=position_offset, attn_mask=mask,
                        cache=layer_cache, cache_offset=cache_offset)
            if layer_cache is not None:
                h, c = out
                new_caches.append(c)
            else:
                h = out
        h = self.final_norm(h)
        if use_cache:
            return h, tuple(new_caches)
        return h

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        shape = (batch_size, int(max_length), cfg.num_attention_heads,
                 cfg.head_dim)
        return tuple(
            (jnp.zeros(shape, dtype or 'float32'),
             jnp.zeros(shape, dtype or 'float32'))
            for _ in range(cfg.num_hidden_layers))


class GPTForCausalLM(Layer, GenerationMixin):
    config_class = GPTConfig

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, h):
        if self.lm_head is not None:
            return self.lm_head(h)
        w = self.gpt.word_embeddings.weight
        return apply_op(lambda hv, wv: hv @ wv.T, h, w, _name='tied_lm_head')

    def pp_blocks(self):
        """Pipeline-parallel protocol — see LlamaForCausalLM.pp_blocks."""
        return 'gpt.layers', self.gpt.layers

    def forward(self, input_ids, position_offset=None, attention_mask=None,
                cache=None, use_cache=False, labels=None, blocks_fn=None,
                cache_offset=None):
        out = self.gpt(input_ids, position_offset=position_offset,
                       attention_mask=attention_mask, cache=cache,
                       use_cache=use_cache, blocks_fn=blocks_fn,
                       cache_offset=cache_offset)
        if use_cache:
            h, new_cache = out
        else:
            h, new_cache = out, None
        logits = self._logits(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                (labels if isinstance(labels, Tensor)
                 else Tensor(to_jax(labels))).reshape([-1]))
            return (loss, logits, new_cache) if use_cache else (loss, logits)
        if use_cache:
            return logits, new_cache
        return logits

    def init_cache(self, batch_size, max_length, dtype=None):
        return self.gpt.init_cache(batch_size, max_length, dtype)
