"""Autoregressive generation with a static-shape KV cache.

Upstream analogue: PaddleNLP `paddlenlp/transformers/generation_utils.py`
(GenerationMixin.generate: greedy / sampling / top-k / top-p with
incremental decode). TPU-native design: instead of growing KV tensors
(which would recompile every step), the cache is allocated once at
`prompt_len + max_new_tokens` and updated in place with
`lax.dynamic_update_slice`; the whole decode is ONE XLA program — a
prefill call followed by a `lax.while_loop` over single-token steps with
early exit when every sequence has emitted EOS.
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import framework
from .. import observability as _obs
from ..jit import functional_call, functional_method, functional_state
from ..tensor import Tensor, to_jax

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _record_spec_stats(rounds: int, emitted: int, accepted: int,
                       proposed: int, source: str = 'generate'):
    """Mirror speculative-decode stats into the shared registry
    (`paddle_spec_*`, labeled by source) so standalone
    `speculative_generate()` and the serving engine's per-slot
    speculation report acceptance through ONE surface instead of
    ad-hoc per-call stats dicts."""
    if not _obs.enabled():
        return
    reg = _obs.get_registry()
    reg.counter('paddle_spec_rounds_total',
                'speculative-decode rounds by source',
                ('source',)).labels(source=source).inc(rounds)
    reg.counter('paddle_spec_emitted_tokens_total',
                'tokens emitted by speculative decode by source',
                ('source',)).labels(source=source).inc(emitted)
    reg.counter('paddle_spec_proposed_drafts_total',
                'draft tokens proposed by source',
                ('source',)).labels(source=source).inc(proposed)
    reg.counter('paddle_spec_accepted_drafts_total',
                'draft tokens accepted by source',
                ('source',)).labels(source=source).inc(accepted)

# warn-once latch for the prompt-already-at-max_length case (tests reset it)
_warned_max_length = [False]


def as_offset(position_offset):
    """Normalize a position offset (None / int / [B] array / Tensor) to a
    traced i32 (scalar, or [B] for per-sequence offsets — left-padded
    prompts give each sequence its own logical position origin)."""
    if position_offset is None:
        return jnp.int32(0)
    if isinstance(position_offset, Tensor):
        return position_offset.value.astype(jnp.int32)
    return jnp.asarray(position_offset, jnp.int32)


def offset_grid(offset, s):
    """Logical positions of `s` consecutive tokens starting at `offset`:
    scalar offset -> [S]; per-sequence [B] offset -> [B, S]."""
    ar = jnp.arange(s, dtype=jnp.int32)
    if jnp.ndim(offset) >= 1:
        return offset[:, None] + ar[None, :]
    return offset + ar


def update_kv_cache(k_cache, v_cache, k, v, offset):
    """Write new K/V blocks into the static decode cache at `offset`.
    All args are Tensors; [B, L, H_kv, D] caches, [B, S, H_kv, D] updates.
    `offset` is a scalar slot shared by the whole batch, or a [B] array of
    per-row slots (the serving engine's slot pool, where every sequence
    decodes at its own position). Returns (k_cache, v_cache) Tensors.
    Shared by every causal-LM family so decode-cache semantics can never
    diverge between models."""
    from ..tensor import apply_op as _apply
    off = offset.value if isinstance(offset, Tensor) else offset

    def upd(c, new):
        new = new.astype(c.dtype)
        if jnp.ndim(off) >= 1:
            return jax.vmap(
                lambda cr, nr, o: jax.lax.dynamic_update_slice(
                    cr, nr, (o, 0, 0)))(c, new, jnp.asarray(off, jnp.int32))
        return jax.lax.dynamic_update_slice(c, new, (0, off, 0, 0))
    return (_apply(upd, k_cache, k, _name='cache_update'),
            _apply(upd, v_cache, v, _name='cache_update'))


def decode_mask(q, k_cache, offset):
    """[1, 1, Sq, L] boolean causal mask for attention over a static cache:
    query at cache slot offset+i sees key slots <= offset+i. (`offset`
    here is the SLOT offset; for unpadded prompts slot == logical
    position.)"""
    from ..tensor import apply_op as _apply

    def fn(qv, kc):
        s, l = qv.shape[1], kc.shape[1]
        q_pos = offset + jnp.arange(s, dtype=jnp.int32)
        k_pos = jnp.arange(l, dtype=jnp.int32)
        return (k_pos[None, :] <= q_pos[:, None])[None, None]
    return _apply(fn, q, k_cache, _name='decode_mask')


def padded_decode_mask(keep, cache_len, cache_offset, sq):
    """[B, 1, Sq, L] boolean mask for decode over a static cache holding a
    left/right-PADDED prompt: slot-causal AND key slot not a pad slot.
    `keep`: [B, S_prompt] bool (1 = real token); generated slots are
    always kept. Self-attention is always allowed so a fully-padded row
    can never produce an all-masked softmax (NaN)."""
    b, s_prompt = keep.shape
    k_slot = jnp.arange(cache_len, dtype=jnp.int32)
    q_slot = cache_offset + jnp.arange(sq, dtype=jnp.int32)
    causal = k_slot[None, :] <= q_slot[:, None]              # [Sq, L]
    keep_full = jnp.concatenate(
        [keep.astype(bool),
         jnp.ones((b, cache_len - s_prompt), bool)], axis=1)  # [B, L]
    self_ok = k_slot[None, :] == q_slot[:, None]             # [Sq, L]
    m = causal[None] & (keep_full[:, None, :] | self_ok[None])
    return m[:, None]                                        # [B,1,Sq,L]


def _process_logits(logits, temperature, top_k, top_p):
    """Filter a [B, V] logits slab for sampling. Static config → traced fine."""
    logits = logits.astype(jnp.float32)
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    v = logits.shape[-1]
    if top_k and 0 < top_k < v:
        # lax.top_k touches k values instead of sorting the whole vocab
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p and top_p < 1.0:
        # full descending sort via top_k(v) — one primitive for both paths
        srt = jax.lax.top_k(logits, v)[0]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1) - 1
        cutoff = jnp.take_along_axis(srt, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, _NEG_INF, logits)
    return logits


def _next_token(logits, key, strategy, temperature, top_k, top_p):
    """Sample the next token; returns (token, its log-prob under the raw
    model distribution)."""
    if strategy == 'greedy_search':
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        filtered = _process_logits(logits, temperature, top_k, top_p)
        tok = jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
    return tok, tok_logp


def cached_forward(model, params, frozen, buffers):
    """The one cached-decode forward contract: returns
    ``fwd(tok, cache, pos_offset, slot, mask) -> (logits, new_cache)``
    running `model` functionally with the given state bound in. Shared by
    the greedy/sampling and beam decode loops below AND by the serving
    engine's slot-pooled decode step (paddle_tpu.serving.engine), so the
    decode-step semantics (position origin, cache slot, mask override)
    can never diverge between the batch and continuous-batching paths.
    `pos_offset`/`slot` may be scalars or per-row [B] arrays."""
    def fwd(tok, cache, pos_offset, slot, mask):
        (logits, new_cache), _ = functional_call(
            model, params, frozen, buffers, (tok,),
            dict(cache=cache, position_offset=pos_offset,
                 cache_offset=slot, attention_mask=mask,
                 use_cache=True))
        return logits, new_cache
    return fwd


class GenerationMixin:
    """Mixed into *ForCausalLM models. Requires the host class to provide:

    - ``init_cache(batch_size, max_length, dtype) -> pytree of jnp arrays``
    - ``forward(input_ids, position_offset=..., cache=..., use_cache=True)``
      returning ``(logits, new_cache)`` when ``use_cache``.
    """

    generation_config: Dict[str, Any] = {}

    def _decode_jit(self, max_new_tokens: int, strategy: str,
                    temperature: float, top_k: int, top_p: float,
                    eos_token_id: int, pad_token_id: int,
                    padded: bool = False, repetition_penalty: float = 1.0,
                    min_new_tokens: int = 0):
        # per-instance cache (a class-level lru_cache would pin every model
        # instance and its compiled executables for the process lifetime)
        cache_key = (max_new_tokens, strategy, temperature, top_k, top_p,
                     eos_token_id, pad_token_id, padded,
                     repetition_penalty, min_new_tokens)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]

        def decode(params, frozen, buffers, ids, keep, cache, key):
            b, s = ids.shape
            total = s + max_new_tokens

            def processors(logits, seen, emit_idx):
                """Upstream logits processors (generation_utils.py):
                CTRL repetition penalty over every token already in the
                sequence, and EOS suppression until min_new_tokens."""
                if repetition_penalty != 1.0:
                    pen = jnp.where(logits > 0,
                                    logits / repetition_penalty,
                                    logits * repetition_penalty)
                    logits = jnp.where(seen, pen, logits)
                if min_new_tokens > 0 and eos_token_id >= 0:
                    v = logits.shape[-1]
                    is_eos = (jnp.arange(v) == eos_token_id)[None, :]
                    logits = jnp.where(
                        is_eos & (emit_idx < min_new_tokens), _NEG_INF,
                        logits)
                return logits

            track_seen = repetition_penalty != 1.0
            if track_seen:
                # OR-accumulate (add then >0): a plain .set() scatter has
                # undefined write order when a pad slot and a real slot
                # carry the same token id
                contrib = (keep if padded
                           else jnp.ones((b, s), bool)).astype(jnp.int32)
                seen0 = (jnp.zeros((b, self.config.vocab_size), jnp.int32)
                         .at[jnp.arange(b)[:, None], ids]
                         .add(contrib)) > 0
            else:
                seen0 = jnp.zeros((b, 1), bool)  # unused placeholder

            fwd = cached_forward(self, params, frozen, buffers)

            if padded:
                # left-padded prompts: per-sequence logical origin
                offsets = jnp.sum(keep, axis=1).astype(jnp.int32) - s  # [B]
                prefill_mask = padded_decode_mask(keep, total, jnp.int32(0),
                                                  s)
            else:
                offsets = jnp.int32(0)
                prefill_mask = None

            def step_mask(i):
                if not padded:
                    return None
                return padded_decode_mask(keep, total, jnp.int32(s) + i, 1)

            def mark_seen(seen, tok):
                if not track_seen:
                    return seen
                return seen.at[jnp.arange(b), tok].set(True)

            # prefill over the whole prompt
            logits, cache = fwd(ids, cache, offsets, jnp.int32(0),
                                prefill_mask)
            key, sub = jax.random.split(key)
            nxt, nxt_logp = _next_token(
                processors(logits[:, -1], seen0, jnp.int32(0)), sub,
                strategy, temperature, top_k, top_p)
            seen = mark_seen(seen0, nxt)
            out = jnp.full((b, max_new_tokens), pad_token_id, jnp.int32)
            scores = jnp.zeros((b,), jnp.float32)
            finished = jnp.zeros((b,), jnp.bool_)

            def cond(state):
                i, _, _, _, _, finished, _, _, _ = state
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(finished)))

            def body(state):
                i, tok, tok_logp, out, cache, finished, scores, key, \
                    seen = state
                # emit `tok` (sampled last round) and count ITS log-prob
                tok = jnp.where(finished, pad_token_id, tok)
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, None], (0, i))
                scores = scores + jnp.where(finished, 0.0, tok_logp)
                newly_done = jnp.logical_or(finished, tok == eos_token_id)
                logits, cache = fwd(tok[:, None].astype(ids.dtype), cache,
                                    offsets + s + i, jnp.int32(s) + i,
                                    step_mask(i))
                key, sub = jax.random.split(key)
                nxt, nxt_logp = _next_token(
                    processors(logits[:, -1], seen, i + 1), sub,
                    strategy, temperature, top_k, top_p)
                seen = mark_seen(seen, nxt)
                return (i + 1, nxt, nxt_logp, out, cache, newly_done,
                        scores, key, seen)

            state = (jnp.int32(0), nxt, nxt_logp, out, cache, finished,
                     scores, key, seen)
            _, _, _, out, _, _, scores, _, _ = jax.lax.while_loop(
                cond, body, state)
            return out, scores

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def _beam_decode_jit(self, max_new_tokens: int, num_beams: int,
                         eos_token_id: int, pad_token_id: int,
                         length_penalty: float, padded: bool = False):
        """Beam search over the static cache (upstream: paddlenlp
        generation_utils BeamSearchScorer path). All K beams of all B
        prompts decode as ONE [B*K] batch; beam reordering is a gather on
        the cache's batch dim inside the loop."""
        cache_key = ('beam', max_new_tokens, num_beams, eos_token_id,
                     pad_token_id, length_penalty, padded)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]
        K = num_beams
        NEG = jnp.float32(-1e9)

        def decode(params, frozen, buffers, ids, keep, cache):
            b, s = ids.shape
            total = s + max_new_tokens
            fwd = cached_forward(self, params, frozen, buffers)

            if padded:
                offsets = jnp.sum(keep, axis=1).astype(jnp.int32) - s  # [B]
                prefill_mask = padded_decode_mask(keep, total, jnp.int32(0),
                                                  s)
            else:
                offsets = jnp.zeros((b,), jnp.int32)
                prefill_mask = None

            logits, cache = fwd(ids, cache, offsets if padded else
                                jnp.int32(0), jnp.int32(0), prefill_mask)
            logp0 = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1)      # [B, V]
            v = logp0.shape[-1]
            scores, tok = jax.lax.top_k(logp0, K)                # [B, K]
            # expand everything beam-wise to a [B*K] batch
            cache = jax.tree_util.tree_map(
                lambda c: jnp.repeat(c, K, axis=0), cache)
            offsets_bk = jnp.repeat(offsets, K)                  # [B*K]
            keep_bk = jnp.repeat(keep, K, axis=0)
            out = jnp.full((b, K, max_new_tokens), pad_token_id, jnp.int32)
            finished = jnp.zeros((b, K), jnp.bool_)
            lengths = jnp.zeros((b, K), jnp.int32)

            def step_mask(i):
                if not padded:
                    return None
                return padded_decode_mask(keep_bk, total, jnp.int32(s) + i,
                                          1)

            def cond(state):
                i = state[0]
                finished = state[5]
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(finished)))

            def body(state):
                (i, tok, out, cache, scores, finished, lengths) = state
                tok = jnp.where(finished, pad_token_id, tok)     # [B, K]
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, :, None], (0, 0, i))
                lengths = lengths + jnp.where(finished, 0, 1)
                finished = jnp.logical_or(finished, tok == eos_token_id)
                logits, cache = fwd(
                    tok.reshape(b * K, 1).astype(ids.dtype), cache,
                    offsets_bk + s + i, jnp.int32(s) + i, step_mask(i))
                logp = jax.nn.log_softmax(
                    logits[:, -1].astype(jnp.float32), -1)       # [B*K, V]
                logp = logp.reshape(b, K, v)
                # finished beams contribute exactly one candidate: their
                # frozen score continuing with pad
                pad_only = jnp.full((v,), NEG).at[pad_token_id].set(0.0)
                logp = jnp.where(finished[:, :, None], pad_only[None, None],
                                 logp)
                cand = scores[:, :, None] + logp                 # [B, K, V]
                scores, flat_idx = jax.lax.top_k(
                    cand.reshape(b, K * v), K)                   # [B, K]
                beam_src = flat_idx // v                         # [B, K]
                nxt = (flat_idx % v).astype(jnp.int32)
                # reorder per-beam state along the beam dim
                out = jnp.take_along_axis(out, beam_src[:, :, None], axis=1)
                finished = jnp.take_along_axis(finished, beam_src, axis=1)
                lengths = jnp.take_along_axis(lengths, beam_src, axis=1)
                flat_src = (jnp.arange(b)[:, None] * K
                            + beam_src).reshape(-1)              # [B*K]
                cache = jax.tree_util.tree_map(
                    lambda c: jnp.take(c, flat_src, axis=0), cache)
                return (i + 1, nxt, out, cache, scores, finished, lengths)

            state = (jnp.int32(0), tok, out, cache, scores, finished,
                     lengths)
            _, _, out, _, scores, _, lengths = jax.lax.while_loop(
                cond, body, state)
            # length-normalized selection (length_penalty=0 -> raw scores)
            norm = jnp.maximum(lengths, 1).astype(jnp.float32) \
                ** jnp.float32(length_penalty)
            best = jnp.argmax(scores / norm, axis=1)             # [B]
            best_out = jnp.take_along_axis(
                out, best[:, None, None], axis=1)[:, 0]          # [B, T]
            best_score = jnp.take_along_axis(
                scores / norm, best[:, None], axis=1)[:, 0]
            return best_out, best_score

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def generate(self, input_ids, max_new_tokens: int = 20,
                 max_length: Optional[int] = None,
                 decode_strategy: str = 'greedy_search',
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 repetition_penalty: float = 1.0, min_new_tokens: int = 0,
                 min_length: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None, use_cache: bool = True,
                 seed: Optional[int] = None,
                 attention_mask=None, **kwargs) -> Tuple[Tensor, Tensor]:
        """Returns (generated ids [B, max_new_tokens], per-sequence score)."""
        if decode_strategy not in ('greedy_search', 'sampling', 'beam_search'):
            raise ValueError(f'unknown decode_strategy {decode_strategy!r}')
        if decode_strategy == 'beam_search' and num_beams < 1:
            raise ValueError('beam_search requires num_beams >= 1')
        if kwargs:
            raise TypeError(f'generate() got unexpected kwargs '
                            f'{sorted(kwargs)}')
        ids = to_jax(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s = ids.shape
        padded = attention_mask is not None
        if padded:
            keep = to_jax(attention_mask).astype(bool)
            if keep.ndim == 1:
                keep = keep[None, :]
            if keep.shape != (b, s):
                raise ValueError(
                    f'attention_mask shape {keep.shape} does not match '
                    f'input_ids shape {(b, s)}')
        else:
            keep = jnp.ones((b, s), bool)
        if max_length is not None:
            max_new_tokens = int(max_length) - s
            if max_new_tokens <= 0:
                # upstream semantics: a prompt that already meets/exceeds
                # max_length gets NO new tokens (the old behavior silently
                # clamped to 1 and decoded past the requested total length)
                if not _warned_max_length[0]:
                    _warned_max_length[0] = True
                    warnings.warn(
                        f'generate(): prompt length {s} already meets '
                        f'max_length={int(max_length)}; returning 0 new '
                        f'tokens. Use max_new_tokens= to request a budget '
                        f'beyond the prompt.', UserWarning, stacklevel=2)
                return (Tensor(jnp.zeros((b, 0), jnp.int32)),
                        Tensor(jnp.zeros((b,), jnp.float32)))
        if min_length is not None:  # upstream name: total-length minimum
            min_new_tokens = max(int(min_length) - s, min_new_tokens)
        if decode_strategy == 'beam_search' and (
                repetition_penalty != 1.0 or min_new_tokens > 0):
            raise NotImplementedError(
                'repetition_penalty/min_new_tokens are supported for '
                'greedy_search and sampling (not beam_search)')
        cfg = getattr(self, 'config', None)
        max_pos = getattr(cfg, 'max_position_embeddings', None)
        if max_pos is not None and s + max_new_tokens > max_pos:
            raise ValueError(
                f'prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds '
                f'max_position_embeddings ({max_pos})')
        if eos_token_id is None:
            eos_token_id = getattr(cfg, 'eos_token_id', -1)
        if pad_token_id is None:
            pad_token_id = getattr(cfg, 'pad_token_id', 0)
        was_training = self.training
        self.eval()
        try:
            params, frozen, buffers = functional_state(self)
            total = s + max_new_tokens
            if decode_strategy == 'beam_search':
                # cache is beam-expanded to [B*K] inside decode after prefill
                cache = self.init_cache(b, total)
                fn = self._beam_decode_jit(int(max_new_tokens),
                                           int(num_beams), int(eos_token_id),
                                           int(pad_token_id),
                                           float(length_penalty),
                                           padded=padded)
                out, scores = fn(params, frozen, buffers, ids, keep, cache)
            else:
                cache = self.init_cache(b, total)
                key = (jax.random.PRNGKey(seed) if seed is not None
                       else framework.next_rng_key())
                fn = self._decode_jit(int(max_new_tokens), decode_strategy,
                                      float(temperature), int(top_k),
                                      float(top_p), int(eos_token_id),
                                      int(pad_token_id), padded=padded,
                                      repetition_penalty=float(
                                          repetition_penalty),
                                      min_new_tokens=int(min_new_tokens))
                out, scores = fn(params, frozen, buffers, ids, keep, cache,
                                 key)
        finally:
            if was_training:
                self.train()
        return Tensor(out), Tensor(scores)

    # ------------------------------------------------------------------
    # speculative decoding (draft-and-verify; upstream analogue:
    # PaddleNLP speculative/draft-model decoding)
    # ------------------------------------------------------------------
    def _spec_decode_jit(self, draft, max_new_tokens: int, k: int,
                         eos_token_id: int, pad_token_id: int):
        """Greedy speculative decode, batch 1: the draft model proposes k
        tokens autoregressively; the target scores all k in ONE cached
        forward and accepts the longest matching prefix plus its own next
        token — output is EXACTLY plain greedy decode, in fewer target
        passes. Stale speculative cache slots need no cleanup: the
        slot-causal decode mask hides every slot above the query position,
        and the next round overwrites them."""
        cache_key = ('spec', id(draft), max_new_tokens, k, eos_token_id,
                     pad_token_id)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]

        def fwd_of(model):
            def fwd(params, frozen, buffers, tok, cache, pos):
                (logits, new_cache), _ = functional_call(
                    model, params, frozen, buffers, (tok,),
                    dict(cache=cache, position_offset=pos, cache_offset=pos,
                         use_cache=True))
                return logits, new_cache
            return fwd

        fwd_t, fwd_d = fwd_of(self), fwd_of(draft)
        pad_cap = max_new_tokens + k + 1   # out buffer with round overshoot

        def decode(pt, ft, bt, pd, fd, bd, ids, cache_t, cache_d):
            s = ids.shape[1]
            logits, cache_t = fwd_t(pt, ft, bt, ids, cache_t, jnp.int32(0))
            _, cache_d = fwd_d(pd, fd, bd, ids, cache_d, jnp.int32(0))
            v = jnp.argmax(logits[0, -1]).astype(jnp.int32)  # pending token
            out = jnp.full((pad_cap,), pad_token_id, jnp.int32)
            out = out.at[0].set(v)   # the pending token is already decided
            state = (jnp.int32(1), v, out, cache_t, cache_d,
                     jnp.int32(0))  # emitted, pending, out, caches, rounds

            def cond(st):
                e, v = st[0], st[1]
                return jnp.logical_and(e < max_new_tokens,
                                       v != eos_token_id)

            def body(st):
                e, v, out, cache_t, cache_d, rounds = st
                p = jnp.int32(s) + e - 1      # logical slot of `v`

                # draft k tokens autoregressively from v
                def draft_body(j, carry):
                    cur, cache_d, drafts = carry
                    lg, cache_d = fwd_d(pd, fd, bd, cur[None, None],
                                        cache_d, p + j)
                    nxt = jnp.argmax(lg[0, -1]).astype(jnp.int32)
                    return nxt, cache_d, drafts.at[j].set(nxt)
                _, cache_d, drafts = jax.lax.fori_loop(
                    0, k, draft_body,
                    (v, cache_d, jnp.zeros((k,), jnp.int32)))

                # target scores [v, d_1..d_k] in one cached forward
                block = jnp.concatenate([v[None], drafts])[None]  # [1, k+1]
                lg, cache_t = fwd_t(pt, ft, bt, block, cache_t, p)
                choice = jnp.argmax(lg[0], axis=-1).astype(jnp.int32)

                # longest accepted draft prefix (stop acceptance at EOS:
                # everything after an emitted EOS is discarded anyway)
                match = (drafts == choice[:k]) & (drafts != eos_token_id)
                a = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
                v_new = choice[a]              # target's token after prefix

                # emit d_1..d_a then v_new at out[e : e+a+1]; positions
                # past a get pad — they are untouched future slots, so
                # the unconditional write is a no-op there
                j = jnp.arange(k + 1)
                draft_ext = jnp.concatenate([drafts, drafts[-1:]])
                emit = jnp.where(j < a, draft_ext,
                                 jnp.where(j == a, v_new, pad_token_id))
                out = out.at[e + j].set(emit, mode='drop')
                return (e + a + 1, v_new, out, cache_t, cache_d,
                        rounds + 1)

            e, _, out, _, _, rounds = jax.lax.while_loop(cond, body, state)
            out = out[:max_new_tokens]
            # blank everything after the first EOS (a round can overshoot)
            if eos_token_id >= 0:
                is_eos = out == eos_token_id
                seen = jnp.cumsum(is_eos.astype(jnp.int32))
                keep = (seen == 0) | (is_eos & (seen == 1))
                out = jnp.where(keep, out, pad_token_id)
            # e stays UNCLAMPED: acceptance stats must count final-round
            # overshoot drafts; the host clamps the emitted-token count
            return out[None], e, rounds

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def speculative_generate(self, draft_model, input_ids,
                             max_new_tokens: int = 20,
                             num_draft_tokens: int = 4,
                             eos_token_id: Optional[int] = None,
                             pad_token_id: Optional[int] = None):
        """Greedy decode accelerated by a smaller draft model (batch 1).
        Returns (ids [1, max_new_tokens], stats dict with `rounds`,
        `emitted`, and `acceptance_rate` = accepted drafts per proposal).
        Output is token-identical to `generate(decode_strategy=
        'greedy_search')` for ANY draft model."""
        ids = to_jax(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        if ids.shape[0] != 1:
            raise ValueError('speculative_generate is a latency '
                             'optimization for a single stream; batch '
                             f'size must be 1, got {ids.shape[0]}')
        cfg = getattr(self, 'config', None)
        if eos_token_id is None:
            eos_token_id = getattr(cfg, 'eos_token_id', -1)
        if pad_token_id is None:
            pad_token_id = getattr(cfg, 'pad_token_id', 0)
        k = int(num_draft_tokens)
        if k < 1:
            raise ValueError('num_draft_tokens must be >= 1')
        was_training = self.training
        draft_was_training = draft_model.training
        self.eval()
        draft_model.eval()
        try:
            pt, ft, bt = functional_state(self)
            pd, fd, bd = functional_state(draft_model)
            s = ids.shape[1]
            total = s + max_new_tokens + k + 2
            cache_t = self.init_cache(1, total)
            cache_d = draft_model.init_cache(1, total)
            fn = self._spec_decode_jit(draft_model, int(max_new_tokens),
                                       k, int(eos_token_id),
                                       int(pad_token_id))
            out, emitted, rounds = fn(pt, ft, bt, pd, fd, bd, ids,
                                      cache_t, cache_d)
        finally:
            if was_training:
                self.train()
            if draft_was_training:
                draft_model.train()
        rounds_i = max(int(rounds), 1)
        # each round is ONE target forward that yields 1 + a tokens; the
        # prefill token is free in both schemes, so accepted drafts total
        # emitted - 1 - rounds. Use the UNCLAMPED emitted count: a final
        # round can overshoot max_new_tokens, and those accepted drafts
        # still measure draft quality / forwards actually saved.
        e_raw = int(emitted)
        emitted_i = min(e_raw, max_new_tokens)
        accepted = max(e_raw - 1 - rounds_i, 0)
        _record_spec_stats(rounds_i, emitted_i, accepted, rounds_i * k)
        return Tensor(out), {
            'rounds': rounds_i, 'emitted': emitted_i,
            'target_forwards_saved': accepted,
            'acceptance_rate': accepted / (rounds_i * k)}


class Seq2SeqGenerationMixin:
    """Mixed into encoder-decoder models (T5). Requires the host class to
    provide, beyond ``forward(decoder_input_ids=..., encoder_output=...,
    encoder_cross_kv=..., attention_mask=..., cache=..., cache_offset=...,
    use_cache=True) -> (logits, new_cache)``:

    - ``encode(input_ids, attention_mask=None) -> encoder hidden``
    - ``cross_kv(encoder_hidden) -> per-decoder-layer (k, v)``
    - ``init_cache(batch_size, max_length, dtype) -> self-attn cache``

    The whole generate is ONE XLA program: encoder forward + per-layer
    cross-attention K/V once, then a `lax.while_loop` of cached
    single-token decoder steps (upstream: paddlenlp generation_utils'
    encoder-decoder path re-runs the encoder outside the loop too, but
    grows the cache — here the cache is static-shape)."""

    def _s2s_decode_jit(self, max_new_tokens: int, strategy: str,
                        temperature: float, top_k: int, top_p: float,
                        eos_token_id: int, pad_token_id: int,
                        start_token_id: int, min_new_tokens: int = 0):
        cache_key = (max_new_tokens, strategy, temperature, top_k, top_p,
                     eos_token_id, pad_token_id, start_token_id,
                     min_new_tokens)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]

        def decode(params, frozen, buffers, enc_ids, enc_keep, cache, key):
            b = enc_ids.shape[0]
            enc_h, _ = functional_method(
                self, 'encode', params, frozen, buffers, (enc_ids,),
                dict(attention_mask=enc_keep))
            cross, _ = functional_method(
                self, 'cross_kv', params, frozen, buffers, (enc_h,), {})

            def processors(logits, emit_idx):
                if min_new_tokens > 0 and eos_token_id >= 0:
                    v = logits.shape[-1]
                    is_eos = (jnp.arange(v) == eos_token_id)[None, :]
                    logits = jnp.where(
                        is_eos & (emit_idx < min_new_tokens), _NEG_INF,
                        logits)
                return logits

            def fwd(tok, cache, slot):
                (logits, new_cache), _ = functional_call(
                    self, params, frozen, buffers, (),
                    dict(decoder_input_ids=tok, encoder_output=enc_h,
                         encoder_cross_kv=cross, attention_mask=enc_keep,
                         cache=cache, cache_offset=slot, use_cache=True))
                return logits, new_cache

            start = jnp.full((b, 1), start_token_id, jnp.int32)
            logits, cache = fwd(start, cache, jnp.int32(0))
            key, sub = jax.random.split(key)
            nxt, nxt_logp = _next_token(
                processors(logits[:, -1], jnp.int32(0)), sub, strategy,
                temperature, top_k, top_p)
            out = jnp.full((b, max_new_tokens), pad_token_id, jnp.int32)
            scores = jnp.zeros((b,), jnp.float32)
            finished = jnp.zeros((b,), jnp.bool_)

            def cond(state):
                i = state[0]
                finished = state[5]
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(finished)))

            def body(state):
                i, tok, tok_logp, out, cache, finished, scores, key = state
                tok = jnp.where(finished, pad_token_id, tok)
                out = jax.lax.dynamic_update_slice(out, tok[:, None], (0, i))
                scores = scores + jnp.where(finished, 0.0, tok_logp)
                newly_done = jnp.logical_or(finished, tok == eos_token_id)
                logits, cache = fwd(tok[:, None], cache, jnp.int32(1) + i)
                key, sub = jax.random.split(key)
                nxt, nxt_logp = _next_token(
                    processors(logits[:, -1], i + 1), sub, strategy,
                    temperature, top_k, top_p)
                return (i + 1, nxt, nxt_logp, out, cache, newly_done,
                        scores, key)

            state = (jnp.int32(0), nxt, nxt_logp, out, cache, finished,
                     scores, key)
            _, _, _, out, _, _, scores, _ = jax.lax.while_loop(
                cond, body, state)
            return out, scores

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def _s2s_beam_decode_jit(self, max_new_tokens: int, num_beams: int,
                             eos_token_id: int, pad_token_id: int,
                             start_token_id: int, length_penalty: float):
        """Beam search for encoder-decoder models: the encoder runs once,
        then cross-attention K/V, the self-attn cache, and the encoder
        mask are beam-expanded to a [B*K] batch (same one-program design
        as the decoder-only beam)."""
        cache_key = ('beam', max_new_tokens, num_beams, eos_token_id,
                     pad_token_id, start_token_id, length_penalty)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]
        K = num_beams
        NEG = jnp.float32(-1e9)

        def decode(params, frozen, buffers, enc_ids, enc_keep, cache):
            b = enc_ids.shape[0]
            enc_h, _ = functional_method(
                self, 'encode', params, frozen, buffers, (enc_ids,),
                dict(attention_mask=enc_keep))
            cross, _ = functional_method(
                self, 'cross_kv', params, frozen, buffers, (enc_h,), {})

            def fwd(tok, cache, cross, enc_h, enc_keep, slot):
                (logits, new_cache), _ = functional_call(
                    self, params, frozen, buffers, (),
                    dict(decoder_input_ids=tok, encoder_output=enc_h,
                         encoder_cross_kv=cross, attention_mask=enc_keep,
                         cache=cache, cache_offset=slot, use_cache=True))
                return logits, new_cache

            start = jnp.full((b, 1), start_token_id, jnp.int32)
            logits, cache = fwd(start, cache, cross, enc_h, enc_keep,
                                jnp.int32(0))
            logp0 = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1)      # [B, V]
            v = logp0.shape[-1]
            scores, tok = jax.lax.top_k(logp0, K)                # [B, K]
            rep = lambda t: jnp.repeat(t, K, axis=0)
            cache = jax.tree_util.tree_map(rep, cache)
            cross_bk = jax.tree_util.tree_map(rep, cross)
            enc_h_bk = rep(enc_h)
            enc_keep_bk = rep(enc_keep)
            out = jnp.full((b, K, max_new_tokens), pad_token_id, jnp.int32)
            finished = jnp.zeros((b, K), jnp.bool_)
            lengths = jnp.zeros((b, K), jnp.int32)

            def cond(state):
                i = state[0]
                finished = state[5]
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(finished)))

            def body(state):
                (i, tok, out, cache, scores, finished, lengths) = state
                tok = jnp.where(finished, pad_token_id, tok)     # [B, K]
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, :, None], (0, 0, i))
                lengths = lengths + jnp.where(finished, 0, 1)
                finished = jnp.logical_or(finished, tok == eos_token_id)
                logits, cache = fwd(
                    tok.reshape(b * K, 1), cache, cross_bk, enc_h_bk,
                    enc_keep_bk, jnp.int32(1) + i)
                logp = jax.nn.log_softmax(
                    logits[:, -1].astype(jnp.float32), -1).reshape(b, K, v)
                pad_only = jnp.full((v,), NEG).at[pad_token_id].set(0.0)
                logp = jnp.where(finished[:, :, None], pad_only[None, None],
                                 logp)
                cand = scores[:, :, None] + logp                 # [B, K, V]
                scores, flat_idx = jax.lax.top_k(
                    cand.reshape(b, K * v), K)                   # [B, K]
                beam_src = flat_idx // v
                nxt = (flat_idx % v).astype(jnp.int32)
                out = jnp.take_along_axis(out, beam_src[:, :, None], axis=1)
                finished = jnp.take_along_axis(finished, beam_src, axis=1)
                lengths = jnp.take_along_axis(lengths, beam_src, axis=1)
                flat_src = (jnp.arange(b)[:, None] * K
                            + beam_src).reshape(-1)              # [B*K]
                cache = jax.tree_util.tree_map(
                    lambda c: jnp.take(c, flat_src, axis=0), cache)
                return (i + 1, nxt, out, cache, scores, finished, lengths)

            state = (jnp.int32(0), tok, out, cache, scores, finished,
                     lengths)
            _, _, out, _, scores, _, lengths = jax.lax.while_loop(
                cond, body, state)
            norm = jnp.maximum(lengths, 1).astype(jnp.float32) \
                ** jnp.float32(length_penalty)
            best = jnp.argmax(scores / norm, axis=1)             # [B]
            best_out = jnp.take_along_axis(
                out, best[:, None, None], axis=1)[:, 0]          # [B, T]
            best_score = jnp.take_along_axis(
                scores / norm, best[:, None], axis=1)[:, 0]
            return best_out, best_score

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def _s2s_spec_decode_jit(self, draft, max_new_tokens: int, k: int,
                             eos_token_id: int, pad_token_id: int,
                             start_token_id: int):
        """Greedy speculative decode for encoder-decoder models (batch 1):
        both models encode their own encoder states once; the decode loop
        is the decoder-only draft-and-verify algorithm with seq2seq
        forwards. Output is EXACTLY plain greedy."""
        cache_key = ('spec', id(draft), max_new_tokens, k, eos_token_id,
                     pad_token_id, start_token_id)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]

        def prep(model, params, frozen, buffers, enc_ids, enc_keep):
            enc_h, _ = functional_method(
                model, 'encode', params, frozen, buffers, (enc_ids,),
                dict(attention_mask=enc_keep))
            cross, _ = functional_method(
                model, 'cross_kv', params, frozen, buffers, (enc_h,), {})

            def fwd(pfb, tok, cache, slot):
                p, f, bu = pfb
                (logits, new_cache), _ = functional_call(
                    model, p, f, bu, (),
                    dict(decoder_input_ids=tok, encoder_output=enc_h,
                         encoder_cross_kv=cross, attention_mask=enc_keep,
                         cache=cache, cache_offset=slot, use_cache=True))
                return logits, new_cache
            return fwd

        pad_cap = max_new_tokens + k + 1

        def decode(pt, ft, bt, pd, fd, bd, enc_ids, enc_keep, cache_t,
                   cache_d):
            fwd_t = prep(self, pt, ft, bt, enc_ids, enc_keep)
            fwd_d = prep(draft, pd, fd, bd, enc_ids, enc_keep)
            start = jnp.full((1, 1), start_token_id, jnp.int32)
            logits, cache_t = fwd_t((pt, ft, bt), start, cache_t,
                                    jnp.int32(0))
            _, cache_d = fwd_d((pd, fd, bd), start, cache_d, jnp.int32(0))
            v = jnp.argmax(logits[0, -1]).astype(jnp.int32)
            out = jnp.full((pad_cap,), pad_token_id, jnp.int32)
            out = out.at[0].set(v)
            state = (jnp.int32(1), v, out, cache_t, cache_d, jnp.int32(0))

            def cond(st):
                return jnp.logical_and(st[0] < max_new_tokens,
                                       st[1] != eos_token_id)

            def body(st):
                e, v, out, cache_t, cache_d, rounds = st
                # decoder slot of `v`: start token sits at 0, emitted
                # token i at slot 1 + i
                p = e                      # == 1 + (e - 1)

                def draft_body(j, carry):
                    cur, cache_d, drafts = carry
                    lg, cache_d = fwd_d((pd, fd, bd), cur[None, None],
                                        cache_d, p + j)
                    nxt = jnp.argmax(lg[0, -1]).astype(jnp.int32)
                    return nxt, cache_d, drafts.at[j].set(nxt)
                _, cache_d, drafts = jax.lax.fori_loop(
                    0, k, draft_body,
                    (v, cache_d, jnp.zeros((k,), jnp.int32)))

                block = jnp.concatenate([v[None], drafts])[None]
                lg, cache_t = fwd_t((pt, ft, bt), block, cache_t, p)
                choice = jnp.argmax(lg[0], axis=-1).astype(jnp.int32)
                match = (drafts == choice[:k]) & (drafts != eos_token_id)
                a = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
                v_new = choice[a]
                j = jnp.arange(k + 1)
                draft_ext = jnp.concatenate([drafts, drafts[-1:]])
                emit = jnp.where(j < a, draft_ext,
                                 jnp.where(j == a, v_new, pad_token_id))
                out = out.at[e + j].set(emit, mode='drop')
                return (e + a + 1, v_new, out, cache_t, cache_d,
                        rounds + 1)

            e, _, out, _, _, rounds = jax.lax.while_loop(cond, body, state)
            out = out[:max_new_tokens]
            if eos_token_id >= 0:
                is_eos = out == eos_token_id
                seen = jnp.cumsum(is_eos.astype(jnp.int32))
                keep = (seen == 0) | (is_eos & (seen == 1))
                out = jnp.where(keep, out, pad_token_id)
            # e stays UNCLAMPED: acceptance stats must count final-round
            # overshoot drafts; the host clamps the emitted-token count
            return out[None], e, rounds

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def speculative_generate(self, draft_model, input_ids,
                             max_new_tokens: int = 20,
                             num_draft_tokens: int = 4,
                             eos_token_id: Optional[int] = None,
                             pad_token_id: Optional[int] = None,
                             decoder_start_token_id: Optional[int] = None,
                             attention_mask=None):
        """Greedy seq2seq decode accelerated by a smaller encoder-decoder
        draft (batch 1). Both models read the same encoder inputs; output
        is token-identical to `generate(decode_strategy='greedy_search')`
        for ANY draft."""
        ids = to_jax(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        if ids.shape[0] != 1:
            raise ValueError('speculative_generate is a latency '
                             'optimization for a single stream; batch '
                             f'size must be 1, got {ids.shape[0]}')
        if attention_mask is not None:
            keep = to_jax(attention_mask).astype(jnp.int32)
            if keep.ndim == 1:
                keep = keep[None, :]
        else:
            keep = jnp.ones(ids.shape, jnp.int32)
        cfg = getattr(self, 'config', None)
        if eos_token_id is None:
            eos_token_id = getattr(cfg, 'eos_token_id', -1)
        if pad_token_id is None:
            pad_token_id = getattr(cfg, 'pad_token_id', 0)
        if decoder_start_token_id is None:
            decoder_start_token_id = getattr(cfg, 'decoder_start_token_id',
                                             0)
        k = int(num_draft_tokens)
        if k < 1:
            raise ValueError('num_draft_tokens must be >= 1')
        was_training = self.training
        draft_was_training = draft_model.training
        self.eval()
        draft_model.eval()
        try:
            pt, ft, bt = functional_state(self)
            pd, fd, bd = functional_state(draft_model)
            total = 1 + max_new_tokens + k + 2
            cache_t = self.init_cache(1, total)
            cache_d = draft_model.init_cache(1, total)
            fn = self._s2s_spec_decode_jit(
                draft_model, int(max_new_tokens), k, int(eos_token_id),
                int(pad_token_id), int(decoder_start_token_id))
            out, emitted, rounds = fn(pt, ft, bt, pd, fd, bd, ids, keep,
                                      cache_t, cache_d)
        finally:
            if was_training:
                self.train()
            if draft_was_training:
                draft_model.train()
        rounds_i = max(int(rounds), 1)
        # unclamped emitted count: final-round overshoot drafts still
        # count as accepted (see the decoder-only mixin)
        e_raw = int(emitted)
        emitted_i = min(e_raw, max_new_tokens)
        accepted = max(e_raw - 1 - rounds_i, 0)
        _record_spec_stats(rounds_i, emitted_i, accepted, rounds_i * k)
        return Tensor(out), {
            'rounds': rounds_i, 'emitted': emitted_i,
            'target_forwards_saved': accepted,
            'acceptance_rate': accepted / (rounds_i * k)}

    def generate(self, input_ids, max_new_tokens: int = 20,
                 max_length: Optional[int] = None,
                 decode_strategy: str = 'greedy_search',
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 min_new_tokens: int = 0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None,
                 decoder_start_token_id: Optional[int] = None,
                 use_cache: bool = True, seed: Optional[int] = None,
                 attention_mask=None, **kwargs) -> Tuple[Tensor, Tensor]:
        """Returns (generated ids [B, max_new_tokens], per-sequence score).
        `input_ids` are ENCODER inputs; decoding starts from
        decoder_start_token_id (upstream T5 convention)."""
        if decode_strategy not in ('greedy_search', 'sampling',
                                   'beam_search'):
            raise ValueError(f'unknown decode_strategy {decode_strategy!r}')
        if decode_strategy == 'beam_search' and num_beams < 1:
            raise ValueError('beam_search requires num_beams >= 1')
        if decode_strategy == 'beam_search' and min_new_tokens > 0:
            raise NotImplementedError(
                'min_new_tokens is supported for greedy_search and '
                'sampling (not beam_search)')
        if kwargs:
            raise TypeError(f'generate() got unexpected kwargs '
                            f'{sorted(kwargs)}')
        ids = to_jax(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s = ids.shape
        if max_length is not None:
            max_new_tokens = max(int(max_length) - 1, 1)
        if attention_mask is not None:
            keep = to_jax(attention_mask).astype(jnp.int32)
            if keep.ndim == 1:
                keep = keep[None, :]
            if keep.shape != (b, s):
                raise ValueError(
                    f'attention_mask shape {keep.shape} does not match '
                    f'input_ids shape {(b, s)}')
        else:
            keep = jnp.ones((b, s), jnp.int32)
        cfg = getattr(self, 'config', None)
        if eos_token_id is None:
            eos_token_id = getattr(cfg, 'eos_token_id', -1)
        if pad_token_id is None:
            pad_token_id = getattr(cfg, 'pad_token_id', 0)
        if decoder_start_token_id is None:
            decoder_start_token_id = getattr(cfg, 'decoder_start_token_id', 0)
        was_training = self.training
        self.eval()
        try:
            params, frozen, buffers = functional_state(self)
            cache = self.init_cache(b, 1 + max_new_tokens)
            if decode_strategy == 'beam_search':
                fn = self._s2s_beam_decode_jit(
                    int(max_new_tokens), int(num_beams), int(eos_token_id),
                    int(pad_token_id), int(decoder_start_token_id),
                    float(length_penalty))
                out, scores = fn(params, frozen, buffers, ids, keep, cache)
            else:
                key = (jax.random.PRNGKey(seed) if seed is not None
                       else framework.next_rng_key())
                fn = self._s2s_decode_jit(
                    int(max_new_tokens), decode_strategy, float(temperature),
                    int(top_k), float(top_p), int(eos_token_id),
                    int(pad_token_id), int(decoder_start_token_id),
                    min_new_tokens=int(min_new_tokens))
                out, scores = fn(params, frozen, buffers, ids, keep, cache,
                                 key)
        finally:
            if was_training:
                self.train()
        return Tensor(out), Tensor(scores)
