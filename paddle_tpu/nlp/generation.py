"""Autoregressive generation with a static-shape KV cache.

Upstream analogue: PaddleNLP `paddlenlp/transformers/generation_utils.py`
(GenerationMixin.generate: greedy / sampling / top-k / top-p with
incremental decode). TPU-native design: instead of growing KV tensors
(which would recompile every step), the cache is allocated once at
`prompt_len + max_new_tokens` and updated in place with
`lax.dynamic_update_slice`; the whole decode is ONE XLA program — a
prefill call followed by a `lax.while_loop` over single-token steps with
early exit when every sequence has emitted EOS.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import framework
from ..jit import functional_call, functional_state
from ..tensor import Tensor, to_jax

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def as_offset(position_offset):
    """Normalize a position offset (None / int / Tensor) to a traced i32."""
    if position_offset is None:
        return jnp.int32(0)
    if isinstance(position_offset, Tensor):
        return position_offset.value
    return jnp.asarray(position_offset, jnp.int32)


def update_kv_cache(k_cache, v_cache, k, v, offset):
    """Write new K/V blocks into the static decode cache at `offset`.
    All args are Tensors; [B, L, H_kv, D] caches, [B, S, H_kv, D] updates.
    Returns (k_cache, v_cache) Tensors. Shared by every causal-LM family
    so decode-cache semantics can never diverge between models."""
    from ..tensor import apply_op as _apply

    def upd(c, new):
        return jax.lax.dynamic_update_slice(c, new.astype(c.dtype),
                                            (0, offset, 0, 0))
    return (_apply(upd, k_cache, k, _name='cache_update'),
            _apply(upd, v_cache, v, _name='cache_update'))


def decode_mask(q, k_cache, offset):
    """[1, 1, Sq, L] boolean causal mask for attention over a static cache:
    query at absolute position offset+i sees key positions <= offset+i."""
    from ..tensor import apply_op as _apply

    def fn(qv, kc):
        s, l = qv.shape[1], kc.shape[1]
        q_pos = offset + jnp.arange(s, dtype=jnp.int32)
        k_pos = jnp.arange(l, dtype=jnp.int32)
        return (k_pos[None, :] <= q_pos[:, None])[None, None]
    return _apply(fn, q, k_cache, _name='decode_mask')


def _process_logits(logits, temperature, top_k, top_p):
    """Filter a [B, V] logits slab for sampling. Static config → traced fine."""
    logits = logits.astype(jnp.float32)
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    v = logits.shape[-1]
    if top_k and 0 < top_k < v:
        kth = jnp.sort(logits, axis=-1)[:, v - top_k][:, None]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p and top_p < 1.0:
        srt = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1) - 1
        cutoff = jnp.take_along_axis(srt, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, _NEG_INF, logits)
    return logits


def _next_token(logits, key, strategy, temperature, top_k, top_p):
    """Sample the next token; returns (token, its log-prob under the raw
    model distribution)."""
    if strategy == 'greedy_search':
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        filtered = _process_logits(logits, temperature, top_k, top_p)
        tok = jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
    return tok, tok_logp


class GenerationMixin:
    """Mixed into *ForCausalLM models. Requires the host class to provide:

    - ``init_cache(batch_size, max_length, dtype) -> pytree of jnp arrays``
    - ``forward(input_ids, position_offset=..., cache=..., use_cache=True)``
      returning ``(logits, new_cache)`` when ``use_cache``.
    """

    generation_config: Dict[str, Any] = {}

    def _decode_jit(self, max_new_tokens: int, strategy: str,
                    temperature: float, top_k: int, top_p: float,
                    eos_token_id: int, pad_token_id: int):
        # per-instance cache (a class-level lru_cache would pin every model
        # instance and its compiled executables for the process lifetime)
        cache_key = (max_new_tokens, strategy, temperature, top_k, top_p,
                     eos_token_id, pad_token_id)
        store = self.__dict__.setdefault('_generate_jit_cache', {})
        if cache_key in store:
            return store[cache_key]
        def decode(params, frozen, buffers, ids, cache, key):
            b, s = ids.shape

            def fwd(tok, cache, offset):
                (logits, new_cache), _ = functional_call(
                    self, params, frozen, buffers, (tok,),
                    dict(cache=cache, position_offset=offset,
                         use_cache=True))
                return logits, new_cache

            # prefill over the whole prompt
            logits, cache = fwd(ids, cache, jnp.int32(0))
            key, sub = jax.random.split(key)
            nxt, nxt_logp = _next_token(logits[:, -1], sub, strategy,
                                        temperature, top_k, top_p)
            out = jnp.full((b, max_new_tokens), pad_token_id, jnp.int32)
            scores = jnp.zeros((b,), jnp.float32)
            finished = jnp.zeros((b,), jnp.bool_)

            def cond(state):
                i, _, _, _, _, finished, _, _ = state
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(finished)))

            def body(state):
                i, tok, tok_logp, out, cache, finished, scores, key = state
                # emit `tok` (sampled last round) and count ITS log-prob
                tok = jnp.where(finished, pad_token_id, tok)
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, None], (0, i))
                scores = scores + jnp.where(finished, 0.0, tok_logp)
                newly_done = jnp.logical_or(finished, tok == eos_token_id)
                logits, cache = fwd(tok[:, None].astype(ids.dtype), cache,
                                    jnp.int32(s) + i)
                key, sub = jax.random.split(key)
                nxt, nxt_logp = _next_token(logits[:, -1], sub, strategy,
                                            temperature, top_k, top_p)
                return (i + 1, nxt, nxt_logp, out, cache, newly_done,
                        scores, key)

            state = (jnp.int32(0), nxt, nxt_logp, out, cache, finished,
                     scores, key)
            _, _, _, out, _, _, scores, _ = jax.lax.while_loop(
                cond, body, state)
            return out, scores

        jitted = jax.jit(decode)
        store[cache_key] = jitted
        return jitted

    def generate(self, input_ids, max_new_tokens: int = 20,
                 max_length: Optional[int] = None,
                 decode_strategy: str = 'greedy_search',
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None, use_cache: bool = True,
                 seed: Optional[int] = None,
                 attention_mask=None, **kwargs) -> Tuple[Tensor, Tensor]:
        """Returns (generated ids [B, max_new_tokens], per-sequence score)."""
        if decode_strategy not in ('greedy_search', 'sampling'):
            raise ValueError(f'unknown decode_strategy {decode_strategy!r}')
        if attention_mask is not None:
            raise NotImplementedError(
                'generate() does not support padded prompts yet; batch '
                'equal-length prompts (an attention_mask would be silently '
                'mis-handled by the static decode cache, so this fails loud)')
        if kwargs:
            raise TypeError(f'generate() got unexpected kwargs '
                            f'{sorted(kwargs)}')
        ids = to_jax(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s = ids.shape
        if max_length is not None:
            max_new_tokens = max(int(max_length) - s, 1)
        cfg = getattr(self, 'config', None)
        max_pos = getattr(cfg, 'max_position_embeddings', None)
        if max_pos is not None and s + max_new_tokens > max_pos:
            raise ValueError(
                f'prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds '
                f'max_position_embeddings ({max_pos})')
        if eos_token_id is None:
            eos_token_id = getattr(cfg, 'eos_token_id', -1)
        if pad_token_id is None:
            pad_token_id = getattr(cfg, 'pad_token_id', 0)
        was_training = self.training
        self.eval()
        try:
            params, frozen, buffers = functional_state(self)
            cache = self.init_cache(b, s + max_new_tokens)
            key = (jax.random.PRNGKey(seed) if seed is not None
                   else framework.next_rng_key())
            fn = self._decode_jit(int(max_new_tokens), decode_strategy,
                                  float(temperature), int(top_k),
                                  float(top_p), int(eos_token_id),
                                  int(pad_token_id))
            out, scores = fn(params, frozen, buffers, ids, cache, key)
        finally:
            if was_training:
                self.train()
        return Tensor(out), Tensor(scores)
