"""T5 encoder-decoder family (relative position bias, RMS layer norm,
unscaled attention, tied embeddings with d_model**-0.5 logit scaling).

Upstream analogue: PaddleNLP `paddlenlp/transformers/t5/modeling.py`
(T5Model / T5ForConditionalGeneration). TPU-native design notes:
- the relative-position bucket map is pure jnp (log-bucketing via
  `jnp.where`, no data-dependent control flow) so the whole encoder and
  the cached decode step trace once under `jax.jit`;
- attention routes through `F.scaled_dot_product_attention` with the
  bias passed as an additive float mask; T5 is unscaled, so q is
  pre-multiplied by sqrt(d_kv) to cancel the SDPA 1/sqrt(d) factor;
- decode uses the same static-slot KV cache as the decoder-only models
  (`lax.dynamic_update_slice`), plus per-layer cross-attention K/V
  computed ONCE from the encoder output — generation never recompiles
  and never re-encodes.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.common_layers import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import RMSNorm
from ..tensor import Tensor, apply_op, to_jax
from .generation import Seq2SeqGenerationMixin, as_offset as _as_offset, \
    update_kv_cache as _update_kv_cache

_NEG = -1e9


class T5Config:
    model_type = 't5'

    def __init__(self, vocab_size=32128, d_model=512, d_kv=64, d_ff=2048,
                 num_layers=6, num_decoder_layers=None, num_heads=8,
                 relative_attention_num_buckets=32,
                 relative_attention_max_distance=128, dropout_rate=0.1,
                 layer_norm_epsilon=1e-6, feed_forward_proj='relu',
                 tie_word_embeddings=True, pad_token_id=0, eos_token_id=1,
                 decoder_start_token_id=0, tensor_parallel=False,
                 sequence_parallel=False, use_recompute=False, **kwargs):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_kv = d_kv
        self.d_ff = d_ff
        self.num_layers = num_layers
        self.num_decoder_layers = (num_decoder_layers
                                   if num_decoder_layers is not None
                                   else num_layers)
        self.num_heads = num_heads
        self.relative_attention_num_buckets = relative_attention_num_buckets
        self.relative_attention_max_distance = relative_attention_max_distance
        self.dropout_rate = dropout_rate
        self.layer_norm_epsilon = layer_norm_epsilon
        self.feed_forward_proj = feed_forward_proj
        self.tie_word_embeddings = tie_word_embeddings
        self.pad_token_id = pad_token_id
        self.eos_token_id = eos_token_id
        self.decoder_start_token_id = decoder_start_token_id
        self.tensor_parallel = tensor_parallel
        self.sequence_parallel = sequence_parallel
        self.use_recompute = use_recompute
        for k, v in kwargs.items():
            setattr(self, k, v)

    @property
    def is_gated_act(self):
        return self.feed_forward_proj.startswith('gated-')

    @property
    def dense_act_fn(self):
        return self.feed_forward_proj.split('-')[-1]

    @classmethod
    def t5_small(cls, **kw):
        return cls(d_model=512, d_kv=64, d_ff=2048, num_layers=6,
                   num_heads=8, **kw)

    @classmethod
    def t5_base(cls, **kw):
        return cls(d_model=768, d_kv=64, d_ff=3072, num_layers=12,
                   num_heads=12, **kw)

    @classmethod
    def t5_large(cls, **kw):
        return cls(d_model=1024, d_kv=64, d_ff=4096, num_layers=24,
                   num_heads=16, **kw)

    @classmethod
    def t5_v1_1_base(cls, **kw):
        return cls(d_model=768, d_kv=64, d_ff=2048, num_layers=12,
                   num_heads=12, feed_forward_proj='gated-gelu',
                   tie_word_embeddings=False, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault('vocab_size', 96)
        kw.setdefault('d_model', 64)
        kw.setdefault('d_kv', 16)
        kw.setdefault('d_ff', 128)
        kw.setdefault('num_layers', 2)
        kw.setdefault('num_heads', 4)
        kw.setdefault('relative_attention_num_buckets', 8)
        kw.setdefault('relative_attention_max_distance', 16)
        kw.setdefault('dropout_rate', 0.0)
        return cls(**kw)


def _col_linear(config, in_f, out_f):
    """Plain Linear, or mp-column-sharded under config.tensor_parallel
    (same wiring as llama.py; upstream: fleet's parallel layers)."""
    if config.tensor_parallel:
        from ..distributed.parallel_layers import ColumnParallelLinear
        return ColumnParallelLinear(in_f, out_f, has_bias=False,
                                    gather_output=False)
    return Linear(in_f, out_f, bias_attr=False)


def _row_linear(config, in_f, out_f):
    if config.tensor_parallel:
        from ..distributed.parallel_layers import RowParallelLinear
        return RowParallelLinear(in_f, out_f, has_bias=False,
                                 input_is_parallel=True)
    return Linear(in_f, out_f, bias_attr=False)


def _split_heads(t, num_heads, d_kv):
    """[B, S, H*D] -> [B, S, H, D] (single definition shared by attention
    and the precomputed cross-attention K/V path)."""
    return apply_op(
        lambda v: v.reshape(v.shape[0], v.shape[1], num_heads, d_kv),
        t, _name='split_heads')


def _relative_position_bucket(rel, bidirectional, num_buckets, max_distance):
    """T5 log-bucketed relative positions (upstream paddlenlp
    t5/modeling.py::T5Attention._relative_position_bucket). `rel` is
    memory_pos - query_pos, int32, any shape."""
    ret = jnp.zeros_like(rel)
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    val_if_large = max_exact + (
        jnp.log(nf / max_exact) / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(Layer):
    """Unscaled multi-head attention with optional learned relative
    position bias; inner dim = num_heads * d_kv (decoupled from d_model)."""

    def __init__(self, config: T5Config, has_relative_attention_bias=False,
                 bidirectional=True):
        super().__init__()
        self.config = config
        self.bidirectional = bidirectional
        self.num_heads = config.num_heads
        self.d_kv = config.d_kv
        inner = config.num_heads * config.d_kv
        self.q = _col_linear(config, config.d_model, inner)
        self.k = _col_linear(config, config.d_model, inner)
        self.v = _col_linear(config, config.d_model, inner)
        self.o = _row_linear(config, inner, config.d_model)
        self.relative_attention_bias = (
            Embedding(config.relative_attention_num_buckets,
                      config.num_heads)
            if has_relative_attention_bias else None)

    def compute_bias(self, query_length, key_length, query_offset=0):
        """[1, H, Sq, Sk] additive bias. `query_offset` shifts the query
        positions (cached decode: the single query sits at slot t)."""
        cfg = self.config
        ctx = query_offset + jnp.arange(query_length, dtype=jnp.int32)
        mem = jnp.arange(key_length, dtype=jnp.int32)
        rel = mem[None, :] - ctx[:, None]
        bucket = _relative_position_bucket(
            rel, self.bidirectional, cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance)
        return apply_op(
            lambda w: jnp.transpose(w[bucket], (2, 0, 1))[None],
            self.relative_attention_bias.weight, _name='t5_rel_bias')

    def forward(self, hidden, key_value_states=None, bias=None, cache=None,
                cache_offset=None):
        """bias: additive float [.., H|1, Sq|1, Sk] (position bias and/or
        padding/causal mask), already combined by the caller.
        cache: self-attn (k,v) static cache updated at `cache_offset`, or
        cross-attn precomputed (k,v) used as-is (key_value_states=None
        marks self-attention)."""
        nh, dk = self.num_heads, self.d_kv

        def split(t):
            return _split_heads(t, nh, dk)

        q = split(self.q(hidden))
        # T5 attention is unscaled; SDPA divides by sqrt(d) — cancel it
        q = apply_op(lambda v: v * math.sqrt(dk), q, _name='t5_unscale')
        new_cache = None
        if key_value_states is not None:        # cross-attention
            if cache is not None:
                kh, vh = cache                  # precomputed, static
            else:
                kh = split(self.k(key_value_states))
                vh = split(self.v(key_value_states))
        else:                                   # self-attention
            kh = split(self.k(hidden))
            vh = split(self.v(hidden))
            if cache is not None:
                slot = _as_offset(cache_offset)
                kc, vc = _update_kv_cache(
                    cache[0], cache[1],
                    kh if isinstance(kh, Tensor) else Tensor(kh),
                    vh if isinstance(vh, Tensor) else Tensor(vh), slot)
                kh, vh = kc, vc
                new_cache = (kc, vc)
        out = F.scaled_dot_product_attention(q, kh, vh, attn_mask=bias)
        out = apply_op(
            lambda t: t.reshape(t.shape[0], t.shape[1], nh * dk),
            out, _name='merge_heads')
        out = self.o(out)
        if new_cache is not None:
            return out, new_cache
        return out


class T5DenseFF(Layer):
    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        act = {'relu': F.relu, 'gelu': lambda x: F.gelu(x, approximate=True),
               'silu': F.silu}[config.dense_act_fn]
        self.act = act
        if config.is_gated_act:
            self.wi_0 = _col_linear(config, config.d_model, config.d_ff)
            self.wi_1 = _col_linear(config, config.d_model, config.d_ff)
        else:
            self.wi = _col_linear(config, config.d_model, config.d_ff)
        self.wo = _row_linear(config, config.d_ff, config.d_model)
        self.dropout = Dropout(config.dropout_rate)

    def forward(self, x):
        if self.config.is_gated_act:
            h = self.act(self.wi_0(x)) * self.wi_1(x)
        else:
            h = self.act(self.wi(x))
        return self.wo(self.dropout(h))


class T5Block(Layer):
    """Pre-norm residual block: ln -> sublayer -> dropout -> add.
    Encoder: self-attn + FF. Decoder: self-attn + cross-attn + FF."""

    def __init__(self, config: T5Config, is_decoder,
                 has_relative_attention_bias=False):
        super().__init__()
        self.is_decoder = is_decoder
        self.self_attn = T5Attention(
            config, has_relative_attention_bias=has_relative_attention_bias,
            bidirectional=not is_decoder)
        self.self_attn_norm = RMSNorm(config.d_model,
                                      epsilon=config.layer_norm_epsilon)
        if is_decoder:
            self.cross_attn = T5Attention(config, bidirectional=True)
            self.cross_attn_norm = RMSNorm(config.d_model,
                                           epsilon=config.layer_norm_epsilon)
        self.ff = T5DenseFF(config)
        self.ff_norm = RMSNorm(config.d_model,
                               epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.dropout_rate)

    def forward(self, hidden, self_bias=None, encoder_hidden=None,
                cross_bias=None, cache=None, cache_offset=None,
                cross_kv=None):
        out = self.self_attn(self.self_attn_norm(hidden), bias=self_bias,
                             cache=cache, cache_offset=cache_offset)
        new_cache = None
        if cache is not None:
            out, new_cache = out
        h = hidden + self.dropout(out)
        if self.is_decoder:
            c = self.cross_attn(self.cross_attn_norm(h),
                                key_value_states=encoder_hidden,
                                bias=cross_bias, cache=cross_kv)
            h = h + self.dropout(c)
        h = h + self.dropout(self.ff(self.ff_norm(h)))
        if cache is not None:
            return h, new_cache
        return h


def _pad_bias(mask):
    """[B, S] keep-mask -> [B, 1, 1, S] additive 0/-1e9 float bias."""
    return apply_op(
        lambda m: jnp.where((m > 0)[:, None, None, :], 0.0, _NEG)
        .astype(jnp.float32), mask, _name='t5_pad_bias')


class T5Stack(Layer):
    def __init__(self, config: T5Config, is_decoder):
        super().__init__()
        self.config = config
        self.is_decoder = is_decoder
        n = config.num_decoder_layers if is_decoder else config.num_layers
        self.block = [T5Block(config, is_decoder,
                              has_relative_attention_bias=(i == 0))
                      for i in range(n)]
        for i, b in enumerate(self.block):
            self.add_sublayer(f'block.{i}', b)
        self.final_layer_norm = RMSNorm(config.d_model,
                                        epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.dropout_rate)

    def forward(self, embeds, attention_mask=None, encoder_hidden=None,
                encoder_attention_mask=None, cache=None, cache_offset=None,
                cross_kv=None):
        h = self.dropout(embeds)
        sp_pin = None
        if self.config.sequence_parallel and cache is None:
            # keep activations sequence-sharded over 'sp' between blocks;
            # GSPMD gathers the sequence only where attention needs it
            # (same design as LlamaModel.forward)
            from jax.sharding import PartitionSpec as P
            from ..distributed.parallel_layers import _constraint
            sp_pin = _constraint(P('dp', 'sp', None))
            h = sp_pin(h)
        s = h.shape[1]
        if cache is not None:
            total = cache[0][0].shape[1]
            slot = _as_offset(cache_offset)
            # query at slots [slot, slot+s); keys valid up to slot+row
            bias = self.block[0].self_attn.compute_bias(
                s, total, query_offset=slot)
            valid = (jnp.arange(total)[None, None, None, :]
                     <= (slot + jnp.arange(s))[None, None, :, None])
            self_bias = apply_op(
                lambda b: b + jnp.where(valid, 0.0, _NEG), bias,
                _name='t5_decode_bias')
        else:
            bias = self.block[0].self_attn.compute_bias(s, s)
            if self.is_decoder:
                causal = (jnp.arange(s)[None, :]
                          <= jnp.arange(s)[:, None])[None, None]
                bias = apply_op(
                    lambda b: b + jnp.where(causal, 0.0, _NEG), bias,
                    _name='t5_causal_bias')
            self_bias = bias
            if attention_mask is not None:
                self_bias = self_bias + _pad_bias(attention_mask)
        cross_bias = None
        if self.is_decoder and encoder_attention_mask is not None:
            cross_bias = _pad_bias(encoder_attention_mask)
        from .. import autograd as _ag
        remat = (self.config.use_recompute and cache is None
                 and _ag._state.functional)
        new_caches = []
        for i, blk in enumerate(self.block):
            layer_cache = None
            if cache is not None:
                kc, vc = cache[i]
                layer_cache = (kc if isinstance(kc, Tensor) else Tensor(kc),
                               vc if isinstance(vc, Tensor) else Tensor(vc))
            if remat:
                # trade FLOPs for HBM: rematerialize the block in backward
                # (upstream: recompute over T5 blocks; here jax.checkpoint,
                # same design as LlamaModel.forward)
                import jax as _jax
                sb = self_bias.value if isinstance(self_bias, Tensor)                     else self_bias
                cb = cross_bias.value if isinstance(cross_bias, Tensor)                     else cross_bias
                eh = encoder_hidden.value                     if isinstance(encoder_hidden, Tensor) else encoder_hidden
                if eh is None:
                    out = Tensor(_jax.checkpoint(
                        lambda hv, b=blk: b(
                            Tensor(hv),
                            self_bias=None if sb is None else Tensor(sb))
                        .value)(h.value))
                else:
                    out = Tensor(_jax.checkpoint(
                        lambda hv, ev, b=blk: b(
                            Tensor(hv),
                            self_bias=None if sb is None else Tensor(sb),
                            encoder_hidden=Tensor(ev),
                            cross_bias=None if cb is None else Tensor(cb))
                        .value)(h.value, eh))
            else:
                out = blk(h, self_bias=self_bias,
                          encoder_hidden=encoder_hidden,
                          cross_bias=cross_bias, cache=layer_cache,
                          cache_offset=cache_offset,
                          cross_kv=None if cross_kv is None
                          else cross_kv[i])
            if layer_cache is not None:
                h, c = out
                new_caches.append(c)
            else:
                h = out
            if sp_pin is not None:
                h = sp_pin(h)
        h = self.dropout(self.final_layer_norm(h))
        if cache is not None:
            return h, tuple(new_caches)
        return h


class T5PretrainedModel(Layer):
    config_class = T5Config
    base_model_prefix = 't5'


class T5Model(T5PretrainedModel):
    """Reference parity: paddlenlp T5Model (shared embedding -> encoder
    stack -> decoder stack with cross-attention)."""

    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            from ..distributed.parallel_layers import VocabParallelEmbedding
            self.shared = VocabParallelEmbedding(config.vocab_size,
                                                 config.d_model)
        else:
            self.shared = Embedding(config.vocab_size, config.d_model)
        self.encoder = T5Stack(config, is_decoder=False)
        self.decoder = T5Stack(config, is_decoder=True)

    def encode(self, input_ids, attention_mask=None):
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(to_jax(input_ids))
        return self.encoder(self.shared(ids), attention_mask=attention_mask)

    def decode(self, decoder_input_ids, encoder_hidden,
               encoder_attention_mask=None, decoder_attention_mask=None,
               cache=None, cache_offset=None, cross_kv=None):
        ids = decoder_input_ids if isinstance(decoder_input_ids, Tensor) \
            else Tensor(to_jax(decoder_input_ids))
        return self.decoder(self.shared(ids),
                            attention_mask=decoder_attention_mask,
                            encoder_hidden=encoder_hidden,
                            encoder_attention_mask=encoder_attention_mask,
                            cache=cache, cache_offset=cache_offset,
                            cross_kv=cross_kv)

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                decoder_attention_mask=None):
        enc = self.encode(input_ids, attention_mask=attention_mask)
        dec_ids = decoder_input_ids \
            if isinstance(decoder_input_ids, Tensor) \
            else Tensor(to_jax(decoder_input_ids))
        dec_embeds = self.shared(dec_ids)
        return self.decoder(dec_embeds, attention_mask=decoder_attention_mask,
                            encoder_hidden=enc,
                            encoder_attention_mask=attention_mask), enc

    def init_cache(self, batch_size, max_length, dtype=None):
        cfg = self.config
        dt = dtype or 'float32'
        shape = (batch_size, int(max_length), cfg.num_heads, cfg.d_kv)
        return tuple((jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                     for _ in range(cfg.num_decoder_layers))

    def cross_kv(self, encoder_hidden):
        """Per-decoder-layer cross-attention (K, V) from the encoder
        output — computed once per generate() call."""
        out = []
        nh, dk = self.config.num_heads, self.config.d_kv
        for blk in self.decoder.block:
            out.append(
                (_split_heads(blk.cross_attn.k(encoder_hidden), nh, dk),
                 _split_heads(blk.cross_attn.v(encoder_hidden), nh, dk)))
        return tuple(out)


class T5ForConditionalGeneration(T5PretrainedModel, Seq2SeqGenerationMixin):
    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        self.t5 = T5Model(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.d_model, config.vocab_size,
                                  bias_attr=False)

    def _shift_right(self, labels):
        """labels -> decoder inputs: prepend decoder_start, drop last,
        map ignore_index (-100) to pad (upstream _shift_right)."""
        cfg = self.config

        def f(lab):
            lab = jnp.asarray(lab)  # host int64 -> canonical int32
            shifted = jnp.concatenate(
                [jnp.full((lab.shape[0], 1), cfg.decoder_start_token_id,
                          lab.dtype), lab[:, :-1]], axis=1)
            return jnp.where(shifted == -100, cfg.pad_token_id, shifted)
        return apply_op(f, labels if isinstance(labels, Tensor)
                        else Tensor(to_jax(labels)), _name='shift_right')

    def _logits(self, h):
        cfg = self.config
        if self.lm_head is not None:
            return self.lm_head(h)
        # tied head: rescale by d_model**-0.5 (upstream T5 does this only
        # in the tied configuration)
        w = self.t5.shared.weight
        scale = cfg.d_model ** -0.5
        return apply_op(lambda hv, wv: (hv * scale) @ wv.T, h, w,
                        _name='tied_lm_head')

    def forward(self, input_ids=None, decoder_input_ids=None,
                attention_mask=None, decoder_attention_mask=None,
                labels=None, encoder_output=None, encoder_cross_kv=None,
                cache=None, cache_offset=None, use_cache=False):
        if labels is not None and decoder_input_ids is None:
            decoder_input_ids = self._shift_right(labels)
        if encoder_output is None:
            encoder_output = self.t5.encode(input_ids,
                                            attention_mask=attention_mask)
        out = self.t5.decode(decoder_input_ids, encoder_output,
                             encoder_attention_mask=attention_mask,
                             decoder_attention_mask=decoder_attention_mask,
                             cache=cache, cache_offset=cache_offset,
                             cross_kv=encoder_cross_kv)
        if cache is not None:
            h, new_cache = out
        else:
            h, new_cache = out, None
        logits = self._logits(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                (labels if isinstance(labels, Tensor)
                 else Tensor(to_jax(labels))).reshape([-1]))
            return loss, logits
        if use_cache:
            return logits, new_cache
        return logits

    # --- Seq2SeqGenerationMixin protocol --------------------------------
    def init_cache(self, batch_size, max_length, dtype=None):
        return self.t5.init_cache(batch_size, max_length, dtype)

    def encode(self, input_ids, attention_mask=None):
        return self.t5.encode(input_ids, attention_mask=attention_mask)

    def cross_kv(self, encoder_hidden):
        return self.t5.cross_kv(encoder_hidden)
