"""paddle.sysconfig (upstream: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os


def get_include() -> str:
    """Directory of the C headers shipped with the package (the native
    runtime sources under csrc/ are the compilation surface here)."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'csrc')


def get_lib() -> str:
    """Directory where compiled native libraries land (the runtime
    builds them on first use under csrc/build/)."""
    return os.path.join(get_include(), 'build')
