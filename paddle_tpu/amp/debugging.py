"""paddle.amp.debugging (upstream: python/paddle/amp/debugging.py):
numerical-health tooling for mixed-precision runs.

Delegates to the framework's debug subsystem: the tensor checker is the
tape-level nan/inf scan (`debug.enable_check_numerics`), and operator
stats ride the same per-op aggregation the profiler's host timer uses."""
from __future__ import annotations

import collections
from typing import Optional

from .. import debug as _debug


class DebugMode:
    """Check granularity (upstream paddle.amp.debugging.DebugMode)."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


def enable_tensor_checker(checker_config=None):
    """Turn on per-op nan/inf scanning of every op output (upstream
    enable_tensor_checker; backed by debug.enable_check_numerics)."""
    _debug.enable_check_numerics()


def disable_tensor_checker():
    _debug.disable_check_numerics()


def check_numerics(tensor, op_type: str = 'tensor', stack_height_limit=1,
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """One-shot nan/inf check of a tensor (upstream
    paddle.amp.debugging.check_numerics)."""
    return _debug.check_numerics(
        tensor, name=op_type,
        raise_on_error=(debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT))


_op_stats: Optional[dict] = None


_prev_hook = None


def enable_operator_stats_collection():
    """Start collecting per-op call/output dtype counts (upstream
    enable_operator_stats_collection). Chains with (does not clobber)
    an active nan/inf checker hook."""
    global _op_stats, _prev_hook
    from .. import tensor as tmod
    if _op_stats is not None:
        # already enabled (re-run cell): reset stats, keep the hook
        _op_stats.clear()
        return
    _op_stats = collections.defaultdict(
        lambda: {'calls': 0, 'dtypes': collections.Counter()})
    _prev_hook = tmod._numerics_hook

    def hook(out, op_name):
        if _prev_hook is not None:
            _prev_hook(out, op_name)
        rec = _op_stats[op_name]
        rec['calls'] += 1
        for leaf in (out if isinstance(out, (tuple, list)) else [out]):
            dt = getattr(leaf, 'dtype', None)
            if dt is not None:
                rec['dtypes'][str(dt)] += 1
    tmod._numerics_hook = hook


def disable_operator_stats_collection():
    """Stop collecting and print the per-op dtype table (upstream
    prints low/high-precision op counts on disable)."""
    global _op_stats, _prev_hook
    from .. import tensor as tmod
    tmod._numerics_hook = _prev_hook
    _prev_hook = None
    if _op_stats is None:
        return
    lines = [f'{"op":<32}{"calls":>8}  dtypes']
    for name, rec in sorted(_op_stats.items()):
        dts = ', '.join(f'{d}x{c}' for d, c in rec['dtypes'].items())
        lines.append(f'{name:<32}{rec["calls"]:>8}  {dts}')
    print('\n'.join(lines))
    _op_stats = None


def collect_operator_numerical_stats():
    """Snapshot of the currently collected stats dict."""
    return {k: {'calls': v['calls'], 'dtypes': dict(v['dtypes'])}
            for k, v in (_op_stats or {}).items()}
