"""Automatic mixed precision (upstream: python/paddle/amp/ —
auto_cast, GradScaler, decorate).

TPU-native design: bf16 is the MXU's native input dtype, so the default
AMP dtype is bfloat16 (fp16 is supported for parity but has no TPU
advantage). `auto_cast` installs a per-thread policy consulted by the
single op choke-point (`tensor.apply_op`): white-list ops (matmul-class,
MXU-bound) compute in the low dtype, black-list ops (softmax/norm/loss,
numerically sensitive reductions) are pinned to fp32, everything else
follows its inputs. O2 ("pure bf16") casts the whole model once and
keeps fp32 master weights inside the optimizer (multi_precision).
GradScaler does dynamic loss scaling for fp16 and is a correct no-op
for bf16 (whose exponent range equals fp32's).
"""
from __future__ import annotations

from . import debugging  # noqa: F401  (paddle.amp.debugging)

import contextlib
import threading
from typing import Iterable, Optional, Sequence

import jax.numpy as jnp

from .. import tensor as _tensor_mod
from ..dtype import convert_dtype
from ..tensor import Tensor

# matmul-class ops: compute-bound on the MXU, safe and fast in bf16/fp16
WHITE_LIST = {
    'matmul', 'mm', 'bmm', 'linear', 'dot', 'einsum', 'addmm', 'mv',
    'conv1d', 'conv2d', 'conv3d', 'conv1d_transpose', 'conv2d_transpose',
    'conv3d_transpose', 'scaled_dot_product_attention', 'bilinear',
}
# numerically-sensitive ops: keep fp32 accumulate/range
BLACK_LIST = {
    'softmax', 'log_softmax', 'cross_entropy', 'nll_loss', 'kl_div',
    'binary_cross_entropy', 'binary_cross_entropy_with_logits',
    'softmax_with_cross_entropy', 'layer_norm', 'batch_norm', 'rms_norm',
    'group_norm', 'instance_norm', 'local_response_norm', 'norm',
    'logsumexp', 'log', 'log2', 'log10', 'log1p', 'exp', 'expm1', 'pow',
    'cumsum', 'cumprod', 'sum', 'mean', 'std', 'var', 'sigmoid_focal_loss',
    'mse_loss', 'l1_loss', 'smooth_l1_loss', 'cosine_similarity',
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = 'O1'
        self.white = WHITE_LIST
        self.black = BLACK_LIST


_state = _AmpState()


def _is_float(v):
    return hasattr(v, 'dtype') and jnp.issubdtype(v.dtype, jnp.floating)


def _cast_inputs(vals, op_name):
    """The apply_op hook: cast raw jax values per the active policy."""
    if not _state.enabled:
        return vals
    if op_name in _state.black:
        return [v.astype(jnp.float32)
                if _is_float(v) and v.dtype != jnp.float32 else v
                for v in vals]
    low = _state.dtype
    if op_name in _state.white or _state.level == 'O2':
        return [v.astype(low)
                if _is_float(v) and v.dtype == jnp.float32 else v
                for v in vals]
    return vals


# install the hook at import time (tensor.apply_op checks for None)
_tensor_mod._amp_cast_hook = _cast_inputs


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list: Optional[Iterable[str]] = None,
              custom_black_list: Optional[Iterable[str]] = None,
              level='O1', dtype='bfloat16', use_promote=True):
    """Context manager enabling mixed-precision op dispatch."""
    if level not in ('O0', 'O1', 'O2'):
        raise ValueError(f'amp level must be O0/O1/O2, got {level!r}')
    cw = set(custom_white_list or ())
    cb = set(custom_black_list or ())
    if cw & cb:  # validate BEFORE touching state (no partial mutation)
        raise ValueError(f'ops in both custom lists: {sorted(cw & cb)}')
    new_dtype = convert_dtype(dtype)
    old = (_state.enabled, _state.dtype, _state.level, _state.white,
           _state.black)
    _state.enabled = bool(enable) and level != 'O0'
    _state.dtype = new_dtype
    _state.level = level
    # custom entries override the built-in opposite list
    _state.white = (WHITE_LIST | cw) - cb
    _state.black = (BLACK_LIST | cb) - cw
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.white,
         _state.black) = old


amp_guard = auto_cast  # legacy alias (upstream paddle.fluid.dygraph.amp)


def decorate(models, optimizers=None, level='O2', dtype='bfloat16',
             master_weight=True, save_dtype=None):
    """O2 decoration: cast model params to the low dtype; keep fp32
    master weights in the optimizer (upstream: paddle.amp.decorate)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == 'O2':
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for opt in opt_list:
            if master_weight:
                opt._multi_precision = True
        if single_model and single_opt:
            return model_list[0], opt_list[0]
        return model_list, opt_list
    return model_list[0] if single_model else model_list


class GradScaler:
    """Dynamic loss scaling (upstream: paddle.amp.GradScaler).

    Needed for fp16 (narrow exponent); for bf16 training this is a
    correct pass-through when `enable=False` (paddle convention) or
    simply never sees inf grads.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss: Tensor) -> Tensor:
        if not self._enable:
            return loss
        return loss * self._scale

    def _params_of(self, optimizer):
        params = optimizer._parameter_list or []
        return [p for p in params if p.grad is not None]

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        flags = []
        for p in self._params_of(optimizer):
            g = p.grad.value * inv
            flags.append(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
            p.grad._data = g
        # one device->host sync for the whole parameter set, not one per
        # tensor (keeps the dispatch pipeline full)
        self._found_inf = bool(flags) and not bool(
            jnp.all(jnp.stack(flags)))
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            self._unscaled = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        # scaled_loss.backward() must already have run
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()


def is_bfloat16_supported(device=None):
    """bf16 is the native matmul dtype of every TPU generation (and CPU
    XLA emulates it), so this is unconditionally true here."""
    return True


def is_float16_supported(device=None):
    return True  # storage-supported on TPU; emulated on CPU
