"""paddle.distribution (upstream: python/paddle/distribution/) — the
distribution zoo with sample/rsample/log_prob/entropy/mean/variance, a
`register_kl` pair-dispatch registry, Independent/TransformedDistribution
wrappers, and invertible transforms.

TPU-native design: every density/statistic is a pure jnp computation
recorded on the tape via apply_op (so log_prob is differentiable and
jit-safe); sampling draws from the stateless threefry stream
(framework.next_rng_key). Reparameterized sampling (`rsample`) is
provided wherever upstream has it — gamma/beta/dirichlet ride
jax.random.gamma's implicit-reparameterization gradients.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .. import framework
from ..tensor import Tensor, apply_op, to_jax
from .transform import (Transform, AffineTransform, ExpTransform,
                        SigmoidTransform, TanhTransform, PowerTransform,
                        AbsTransform, ChainTransform)

__all__ = [
    'Distribution', 'Normal', 'Uniform', 'Categorical', 'Bernoulli',
    'Beta', 'Dirichlet', 'Gamma', 'Exponential', 'Geometric', 'Gumbel',
    'Laplace', 'LogNormal', 'Multinomial', 'Poisson', 'StudentT',
    'Independent', 'TransformedDistribution', 'kl_divergence',
    'register_kl', 'Transform', 'AffineTransform', 'ExpTransform',
    'SigmoidTransform', 'TanhTransform', 'PowerTransform', 'AbsTransform',
    'ChainTransform',
]

_EULER = 0.5772156649015329  # Euler–Mascheroni


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(to_jax(x),
                                                              jnp.float32))


def _key(seed=0):
    return jax.random.key(seed) if seed else framework.next_rng_key()


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value), _name='exp')


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(loc, scale):
            base = jnp.broadcast_shapes(loc.shape, scale.shape)
            eps = jax.random.normal(k, shape + base, jnp.float32)
            return loc + scale * eps
        return apply_op(f, self.loc, self.scale, _name='normal_sample')

    rsample = sample  # reparameterized by construction

    def log_prob(self, value):
        def f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='normal_log_prob')

    def entropy(self):
        return apply_op(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            self.scale, _name='normal_entropy')

    def kl_divergence(self, other: 'Normal'):
        return kl_divergence(self, other)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_t(low)
        self.high = _as_t(high)

    @property
    def mean(self):
        return apply_op(lambda lo, hi: (lo + hi) / 2, self.low, self.high,
                        _name='uniform_mean')

    @property
    def variance(self):
        return apply_op(lambda lo, hi: (hi - lo) ** 2 / 12.0, self.low,
                        self.high, _name='uniform_var')

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(lo, hi):
            base = jnp.broadcast_shapes(lo.shape, hi.shape)
            u = jax.random.uniform(k, shape + base, jnp.float32)
            return lo + (hi - lo) * u
        return apply_op(f, self.low, self.high, _name='uniform_sample')

    rsample = sample

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op(f, _as_t(value), self.low, self.high,
                        _name='uniform_log_prob')

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), self.low,
                        self.high, _name='uniform_entropy')


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _as_t(logits)

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)
        return apply_op(
            lambda lg: jax.random.categorical(
                k, lg, axis=-1, shape=shape + lg.shape[:-1]),
            self.logits, _name='categorical_sample')

    def probs(self, value=None):
        p = apply_op(lambda lg: jax.nn.softmax(lg, axis=-1), self.logits,
                     _name='softmax')
        if value is None:
            return p
        return apply_op(
            lambda pv, idx: jnp.take_along_axis(
                pv, idx.astype(jnp.int32)[..., None], axis=-1)[..., 0],
            p, _as_t(value), _name='categorical_probs')

    def log_prob(self, value):
        def f(lg, idx):
            logp = jax.nn.log_softmax(lg, axis=-1)
            idx = idx.astype(jnp.int32).reshape(lg.shape[:-1])
            return jnp.take_along_axis(logp, idx[..., None],
                                       axis=-1)[..., 0]
        return apply_op(f, self.logits, _as_t(value),
                        _name='categorical_log_prob')

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return apply_op(f, self.logits, _name='categorical_entropy')

    def kl_divergence(self, other: 'Categorical'):
        return kl_divergence(self, other)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _as_t(probs)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return apply_op(lambda p: p * (1 - p), self.probs,
                        _name='bernoulli_var')

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)
        return apply_op(
            lambda p: jax.random.bernoulli(
                k, p, shape + p.shape).astype(jnp.float32),
            self.probs, _name='bernoulli_sample')

    def log_prob(self, value):
        def f(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op(f, self.probs, _as_t(value),
                        _name='bernoulli_log_prob')

    def entropy(self):
        def f(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op(f, self.probs, _name='bernoulli_entropy')


class Beta(Distribution):
    """Beta(alpha, beta) on (0, 1) (upstream distribution/beta.py)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _as_t(alpha)
        self.beta = _as_t(beta)

    @property
    def mean(self):
        return apply_op(lambda a, b: a / (a + b), self.alpha, self.beta,
                        _name='beta_mean')

    @property
    def variance(self):
        return apply_op(
            lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
            self.alpha, self.beta, _name='beta_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(a, b):
            base = jnp.broadcast_shapes(a.shape, b.shape)
            return jax.random.beta(k, a, b, shape + base)
        return apply_op(f, self.alpha, self.beta, _name='beta_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, a, b):
            logbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - logbeta
        return apply_op(f, _as_t(value), self.alpha, self.beta,
                        _name='beta_log_prob')

    def entropy(self):
        def f(a, b):
            logbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
            return (logbeta - (a - 1) * jsp.digamma(a)
                    - (b - 1) * jsp.digamma(b)
                    + (a + b - 2) * jsp.digamma(a + b))
        return apply_op(f, self.alpha, self.beta, _name='beta_entropy')


class Dirichlet(Distribution):
    """Dirichlet(concentration) on the simplex (upstream
    distribution/dirichlet.py)."""

    def __init__(self, concentration, name=None):
        self.concentration = _as_t(concentration)

    @property
    def mean(self):
        return apply_op(lambda c: c / jnp.sum(c, -1, keepdims=True),
                        self.concentration, _name='dirichlet_mean')

    @property
    def variance(self):
        def f(c):
            c0 = jnp.sum(c, -1, keepdims=True)
            m = c / c0
            return m * (1 - m) / (c0 + 1)
        return apply_op(f, self.concentration, _name='dirichlet_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(c):
            # gamma-normalization construction keeps implicit-reparam grads
            g = jax.random.gamma(k, c, shape + c.shape)
            return g / jnp.sum(g, axis=-1, keepdims=True)
        return apply_op(f, self.concentration, _name='dirichlet_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, c):
            logbeta = (jnp.sum(jsp.gammaln(c), -1)
                       - jsp.gammaln(jnp.sum(c, -1)))
            return jnp.sum((c - 1) * jnp.log(v), -1) - logbeta
        return apply_op(f, _as_t(value), self.concentration,
                        _name='dirichlet_log_prob')

    def entropy(self):
        def f(c):
            c0 = jnp.sum(c, -1)
            kdim = c.shape[-1]
            logbeta = jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(c0)
            return (logbeta + (c0 - kdim) * jsp.digamma(c0)
                    - jnp.sum((c - 1) * jsp.digamma(c), -1))
        return apply_op(f, self.concentration, _name='dirichlet_entropy')


class Gamma(Distribution):
    """Gamma(concentration k, rate β) (upstream distribution/gamma.py)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _as_t(concentration)
        self.rate = _as_t(rate)

    @property
    def mean(self):
        return apply_op(lambda a, b: a / b, self.concentration, self.rate,
                        _name='gamma_mean')

    @property
    def variance(self):
        return apply_op(lambda a, b: a / (b * b), self.concentration,
                        self.rate, _name='gamma_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(a, b):
            base = jnp.broadcast_shapes(a.shape, b.shape)
            return jax.random.gamma(k, jnp.broadcast_to(a, shape + base)) \
                / b
        return apply_op(f, self.concentration, self.rate,
                        _name='gamma_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, a, b):
            return (a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                    - jsp.gammaln(a))
        return apply_op(f, _as_t(value), self.concentration, self.rate,
                        _name='gamma_log_prob')

    def entropy(self):
        def f(a, b):
            return (a - jnp.log(b) + jsp.gammaln(a)
                    + (1 - a) * jsp.digamma(a))
        return apply_op(f, self.concentration, self.rate,
                        _name='gamma_entropy')


class Exponential(Distribution):
    """Exponential(rate) (upstream distribution/exponential.py)."""

    def __init__(self, rate, name=None):
        self.rate = _as_t(rate)

    @property
    def mean(self):
        return apply_op(lambda r: 1.0 / r, self.rate, _name='exp_mean')

    @property
    def variance(self):
        return apply_op(lambda r: 1.0 / (r * r), self.rate,
                        _name='exp_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(r):
            u = jax.random.uniform(k, shape + r.shape, jnp.float32,
                                   minval=jnp.finfo(jnp.float32).tiny)
            return -jnp.log(u) / r
        return apply_op(f, self.rate, _name='exponential_sample')

    sample = rsample

    def log_prob(self, value):
        return apply_op(lambda v, r: jnp.log(r) - r * v, _as_t(value),
                        self.rate, _name='exponential_log_prob')

    def entropy(self):
        return apply_op(lambda r: 1.0 - jnp.log(r), self.rate,
                        _name='exponential_entropy')


class Geometric(Distribution):
    """Geometric(probs): failures before the first success, support
    {0, 1, 2, ...}, pmf(k) = (1-p)^k p (upstream
    distribution/geometric.py; same convention as torch/scipy-shifted)."""

    def __init__(self, probs, name=None):
        self.probs = _as_t(probs)

    @property
    def mean(self):
        return apply_op(lambda p: (1 - p) / p, self.probs,
                        _name='geometric_mean')

    @property
    def variance(self):
        return apply_op(lambda p: (1 - p) / (p * p), self.probs,
                        _name='geometric_var')

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(p):
            u = jax.random.uniform(k, shape + p.shape, jnp.float32,
                                   minval=jnp.finfo(jnp.float32).tiny)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return apply_op(f, self.probs, _name='geometric_sample')

    def log_prob(self, value):
        return apply_op(
            lambda v, p: v * jnp.log1p(-p) + jnp.log(p), _as_t(value),
            self.probs, _name='geometric_log_prob')

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return apply_op(f, self.probs, _name='geometric_entropy')


class Gumbel(Distribution):
    """Gumbel(loc, scale) (upstream distribution/gumbel.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        return apply_op(lambda l, s: l + _EULER * s, self.loc, self.scale,
                        _name='gumbel_mean')

    @property
    def variance(self):
        return apply_op(lambda l, s: (math.pi ** 2 / 6.0) * s * s,
                        self.loc, self.scale, _name='gumbel_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(l, s):
            base = jnp.broadcast_shapes(l.shape, s.shape)
            g = jax.random.gumbel(k, shape + base, jnp.float32)
            return l + s * g
        return apply_op(f, self.loc, self.scale, _name='gumbel_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='gumbel_log_prob')

    def entropy(self):
        return apply_op(lambda l, s: jnp.log(s) + 1.0 + _EULER, self.loc,
                        self.scale, _name='gumbel_entropy')


class Laplace(Distribution):
    """Laplace(loc, scale) (upstream distribution/laplace.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply_op(lambda s: 2.0 * s * s, self.scale,
                        _name='laplace_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(l, s):
            base = jnp.broadcast_shapes(l.shape, s.shape)
            u = jax.random.uniform(k, shape + base, jnp.float32,
                                   minval=-0.5 + 1e-7, maxval=0.5)
            return l - s * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))
        return apply_op(f, self.loc, self.scale, _name='laplace_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, l, s):
            return -jnp.abs(v - l) / s - jnp.log(2 * s)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='laplace_log_prob')

    def entropy(self):
        return apply_op(lambda s: 1.0 + jnp.log(2 * s), self.scale,
                        _name='laplace_entropy')


class LogNormal(Distribution):
    """LogNormal(loc, scale): exp of a Normal (upstream
    distribution/lognormal.py)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        self._base = Normal(loc, scale)

    @property
    def mean(self):
        return apply_op(lambda l, s: jnp.exp(l + s * s / 2), self.loc,
                        self.scale, _name='lognormal_mean')

    @property
    def variance(self):
        return apply_op(
            lambda l, s: (jnp.exp(s * s) - 1) * jnp.exp(2 * l + s * s),
            self.loc, self.scale, _name='lognormal_var')

    def rsample(self, shape=(), seed=0):
        z = self._base.rsample(shape, seed)
        return apply_op(jnp.exp, z, _name='lognormal_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, l, s):
            logv = jnp.log(v)
            return (-((logv - l) ** 2) / (2 * s * s) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi) - logv)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='lognormal_log_prob')

    def entropy(self):
        # base normal entropy + E[log x] = loc
        return apply_op(
            lambda l, s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s) + l,
            self.loc, self.scale, _name='lognormal_entropy')


class Multinomial(Distribution):
    """Multinomial(total_count, probs) (upstream
    distribution/multinomial.py). total_count is a python int (static
    under jit, as upstream requires)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _as_t(probs)

    @property
    def mean(self):
        n = self.total_count
        return apply_op(lambda p: n * p, self.probs,
                        _name='multinomial_mean')

    @property
    def variance(self):
        n = self.total_count
        return apply_op(lambda p: n * p * (1 - p), self.probs,
                        _name='multinomial_var')

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)
        n = self.total_count

        def f(p):
            logits = jnp.log(p)
            kdim = p.shape[-1]
            # n categorical draws -> one-hot counts; [n, shape..., batch]
            draws = jax.random.categorical(
                k, logits, axis=-1, shape=(n,) + shape + p.shape[:-1])
            return jnp.sum(jax.nn.one_hot(draws, kdim, dtype=jnp.float32),
                           axis=0)
        return apply_op(f, self.probs, _name='multinomial_sample')

    def log_prob(self, value):
        def f(v, p):
            # xlogy: 0 * log(0) = 0 for zero-prob categories with 0 count
            return (jsp.gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(jsp.gammaln(v + 1), -1)
                    + jnp.sum(jsp.xlogy(v, p), -1))
        return apply_op(f, _as_t(value), self.probs,
                        _name='multinomial_log_prob')


class Poisson(Distribution):
    """Poisson(rate) (upstream distribution/poisson.py)."""

    def __init__(self, rate, name=None):
        self.rate = _as_t(rate)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)
        return apply_op(
            lambda r: jax.random.poisson(
                k, r, shape + r.shape).astype(jnp.float32),
            self.rate, _name='poisson_sample')

    def log_prob(self, value):
        return apply_op(
            lambda v, r: v * jnp.log(r) - r - jsp.gammaln(v + 1),
            _as_t(value), self.rate, _name='poisson_log_prob')

    def entropy(self):
        """Truncated-series entropy -Σ pmf·log pmf over k ≤ rate+10σ+10
        (the same bounded-support evaluation upstream uses; needs a
        concrete rate, i.e. eager mode)."""
        rmax = float(jnp.max(to_jax(self.rate)))
        upper = int(rmax + 10.0 * math.sqrt(max(rmax, 1.0)) + 10)

        def f(r):
            ks = jnp.arange(upper + 1, dtype=jnp.float32)
            ks = ks.reshape((upper + 1,) + (1,) * r.ndim)
            logp = ks * jnp.log(r) - r - jsp.gammaln(ks + 1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=0)
        return apply_op(f, self.rate, _name='poisson_entropy')


class StudentT(Distribution):
    """StudentT(df, loc, scale) (upstream distribution/student_t.py)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _as_t(df)
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def f(df, s):
            return jnp.where(df > 2, s * s * df / (df - 2), jnp.inf)
        return apply_op(f, self.df, self.scale, _name='studentt_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(df, l, s):
            base = jnp.broadcast_shapes(df.shape, l.shape, s.shape)
            t = jax.random.t(k, df, shape + base)
            return l + s * t
        return apply_op(f, self.df, self.loc, self.scale,
                        _name='studentt_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, df, l, s):
            z = (v - l) / s
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))
        return apply_op(f, _as_t(value), self.df, self.loc, self.scale,
                        _name='studentt_log_prob')

    def entropy(self):
        def f(df, s):
            half = (df + 1) / 2
            logbeta = (jsp.gammaln(df / 2) + jsp.gammaln(0.5)
                       - jsp.gammaln(df / 2 + 0.5))  # log B(df/2, 1/2)
            return (half * (jsp.digamma(half) - jsp.digamma(df / 2))
                    + 0.5 * jnp.log(df) + logbeta + jnp.log(s))
        return apply_op(f, self.df, self.scale, _name='studentt_entropy')


class Independent(Distribution):
    """Reinterpret the last `reinterpreted_batch_ndims` batch dims of a
    base distribution as event dims (upstream
    distribution/independent.py): log_prob/entropy sum over them."""

    def __init__(self, base, reinterpreted_batch_ndims=1, name=None):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)

    def _sum_event(self, t):
        n = self.reinterpreted_batch_ndims
        if n == 0:
            return t
        return apply_op(
            lambda v: jnp.sum(v, axis=tuple(range(v.ndim - n, v.ndim))),
            t, _name='independent_sum')

    def sample(self, shape=(), seed=0):
        return self.base.sample(shape, seed)

    def rsample(self, shape=(), seed=0):
        return self.base.rsample(shape, seed)

    def log_prob(self, value):
        return self._sum_event(self.base.log_prob(value))

    def entropy(self):
        return self._sum_event(self.base.entropy())

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class TransformedDistribution(Distribution):
    """Push a base distribution through invertible transforms (upstream
    distribution/transformed_distribution.py)."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = [transforms] if isinstance(transforms, Transform) \
            else list(transforms)

    def _fwd(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=(), seed=0):
        return self._fwd(self.base.sample(shape, seed))

    def rsample(self, shape=(), seed=0):
        return self._fwd(self.base.rsample(shape, seed))

    def log_prob(self, value):
        y = _as_t(value)
        lp = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ld = t.forward_log_det_jacobian(x)
            lp = ld if lp is None else lp + ld
            y = x
        base_lp = self.base.log_prob(y)
        return base_lp - lp if lp is not None else base_lp


# ---------------------------------------------------------------------------
# KL registry (upstream distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    """Decorator registering fn(p, q) as KL(p||q) for the class pair."""
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    """Pair-dispatched KL(p||q); falls back along the MRO like upstream's
    dispatch."""
    matches = [
        (tp, tq) for (tp, tq) in _KL_REGISTRY
        if isinstance(p, tp) and isinstance(q, tq)]
    if not matches:
        raise NotImplementedError(
            f'kl_divergence({type(p).__name__}, {type(q).__name__}) '
            f'is not registered')
    # most-derived match first (smallest combined MRO distance)
    tp, tq = min(matches, key=lambda m: (type(p).__mro__.index(m[0])
                                         + type(q).__mro__.index(m[1])))
    return _KL_REGISTRY[(tp, tq)](p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def f(l1, s1, l2, s2):
        return (jnp.log(s2 / s1) + (s1 * s1 + (l1 - l2) ** 2)
                / (2 * s2 * s2) - 0.5)
    return apply_op(f, p.loc, p.scale, q.loc, q.scale, _name='kl_normal')


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def f(a, b):
        pa = jax.nn.log_softmax(a, axis=-1)
        pb = jax.nn.log_softmax(b, axis=-1)
        return jnp.sum(jnp.exp(pa) * (pa - pb), axis=-1)
    return apply_op(f, p.logits, q.logits, _name='kl_categorical')


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def f(a, b):
        a = jnp.clip(a, 1e-7, 1 - 1e-7)
        b = jnp.clip(b, 1e-7, 1 - 1e-7)
        return (a * (jnp.log(a) - jnp.log(b))
                + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))
    return apply_op(f, p.probs, q.probs, _name='kl_bernoulli')


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def f(a1, b1, a2, b2):
        logb = lambda a, b: (jsp.gammaln(a) + jsp.gammaln(b)  # noqa: E731
                             - jsp.gammaln(a + b))
        return (logb(a2, b2) - logb(a1, b1)
                + (a1 - a2) * jsp.digamma(a1)
                + (b1 - b2) * jsp.digamma(b1)
                + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1))
    return apply_op(f, p.alpha, p.beta, q.alpha, q.beta, _name='kl_beta')


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def f(c1, c2):
        c10 = jnp.sum(c1, -1)
        c20 = jnp.sum(c2, -1)
        return (jsp.gammaln(c10) - jsp.gammaln(c20)
                - jnp.sum(jsp.gammaln(c1) - jsp.gammaln(c2), -1)
                + jnp.sum((c1 - c2) * (jsp.digamma(c1)
                                       - jsp.digamma(c10)[..., None]), -1))
    return apply_op(f, p.concentration, q.concentration,
                    _name='kl_dirichlet')


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def f(a1, b1, a2, b2):
        return ((a1 - a2) * jsp.digamma(a1) - jsp.gammaln(a1)
                + jsp.gammaln(a2) + a2 * (jnp.log(b1) - jnp.log(b2))
                + a1 * (b2 / b1 - 1.0))
    return apply_op(f, p.concentration, p.rate, q.concentration, q.rate,
                    _name='kl_gamma')


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return apply_op(
        lambda r1, r2: jnp.log(r1) - jnp.log(r2) + r2 / r1 - 1.0,
        p.rate, q.rate, _name='kl_exponential')


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    def f(p1, p2):
        return ((1 - p1) / p1 * (jnp.log1p(-p1) - jnp.log1p(-p2))
                + jnp.log(p1) - jnp.log(p2))
    return apply_op(f, p.probs, q.probs, _name='kl_geometric')


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + d / s2
                + s1 / s2 * jnp.exp(-d / s1) - 1.0)
    return apply_op(f, p.loc, p.scale, q.loc, q.scale, _name='kl_laplace')


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    # KL is invariant under the shared exp() pushforward
    return _kl_normal(Normal(p.loc, p.scale), Normal(q.loc, q.scale))


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return apply_op(
        lambda r1, r2: r1 * (jnp.log(r1) - jnp.log(r2)) + r2 - r1,
        p.rate, q.rate, _name='kl_poisson')


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def f(lo1, hi1, lo2, hi2):
        inside = (lo2 <= lo1) & (hi1 <= hi2)
        return jnp.where(inside, jnp.log((hi2 - lo2) / (hi1 - lo1)),
                         jnp.inf)
    return apply_op(f, p.low, p.high, q.low, q.high, _name='kl_uniform')


@register_kl(Gumbel, Gumbel)
def _kl_gumbel(p, q):
    def f(l1, s1, l2, s2):
        # KL(G1||G2) = log(s2/s1) + γ(s1/s2 - 1) + exp((l2-l1)/s2
        #   + lgamma(1 + s1/s2)-ish — no simple closed form for s1≠s2;
        # exact for equal scales, Taylor-free formula below covers the
        # general case via E_p[z2 + exp(-z2)] with z2=(x-l2)/s2:
        # E_p[z2] = (l1 - l2)/s2 + γ s1/s2
        # E_p[exp(-z2)] = exp((l2 - l1)/s2) Γ(1 + s1/s2)
        ez = (l1 - l2) / s2 + _EULER * s1 / s2
        ee = jnp.exp((l2 - l1) / s2) * jnp.exp(jsp.gammaln(1 + s1 / s2))
        entropy_p = jnp.log(s1) + 1.0 + _EULER
        return ez + ee + jnp.log(s2) - entropy_p
    return apply_op(f, p.loc, p.scale, q.loc, q.scale, _name='kl_gumbel')


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p.reinterpreted_batch_ndims != q.reinterpreted_batch_ndims:
        raise NotImplementedError(
            'kl_divergence between Independents with different '
            'reinterpreted_batch_ndims')
    return p._sum_event(kl_divergence(p.base, q.base))


# the remaining upstream families live in families2.py; imported last so
# its `from . import Distribution, ...` sees the bases defined above
from .families2 import (Binomial, Cauchy, Chi2,  # noqa: E402
                        ContinuousBernoulli, LKJCholesky,
                        MultivariateNormal)

__all__ += ['Binomial', 'Cauchy', 'Chi2', 'ContinuousBernoulli',
            'LKJCholesky', 'MultivariateNormal']
