"""paddle.distribution (upstream: python/paddle/distribution/) —
Normal/Uniform/Categorical/Bernoulli with sample/log_prob/entropy/kl,
built on the stateless PRNG (framework.next_rng_key) and tape ops so
log_prob is differentiable."""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import framework
from ..tensor import Tensor, apply_op, to_jax

__all__ = ['Distribution', 'Normal', 'Uniform', 'Categorical',
           'Bernoulli', 'kl_divergence']


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(to_jax(x),
                                                              jnp.float32))


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value), _name='exp')


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    def sample(self, shape=(), seed=0):
        k = jax.random.key(seed) if seed else framework.next_rng_key()
        shape = tuple(shape)

        def f(loc, scale):
            base = jnp.broadcast_shapes(loc.shape, scale.shape)
            eps = jax.random.normal(k, shape + base, jnp.float32)
            return loc + scale * eps
        return apply_op(f, self.loc, self.scale, _name='normal_sample')

    rsample = sample  # reparameterized by construction

    def log_prob(self, value):
        def f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='normal_log_prob')

    def entropy(self):
        return apply_op(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            self.scale, _name='normal_entropy')

    def kl_divergence(self, other: 'Normal'):
        def f(l1, s1, l2, s2):
            return (jnp.log(s2 / s1) + (s1 * s1 + (l1 - l2) ** 2)
                    / (2 * s2 * s2) - 0.5)
        return apply_op(f, self.loc, self.scale, other.loc, other.scale,
                        _name='normal_kl')


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_t(low)
        self.high = _as_t(high)

    def sample(self, shape=(), seed=0):
        k = jax.random.key(seed) if seed else framework.next_rng_key()
        shape = tuple(shape)

        def f(lo, hi):
            base = jnp.broadcast_shapes(lo.shape, hi.shape)
            u = jax.random.uniform(k, shape + base, jnp.float32)
            return lo + (hi - lo) * u
        return apply_op(f, self.low, self.high, _name='uniform_sample')

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op(f, _as_t(value), self.low, self.high,
                        _name='uniform_log_prob')

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), self.low,
                        self.high, _name='uniform_entropy')


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _as_t(logits)

    def sample(self, shape=(), seed=0):
        k = jax.random.key(seed) if seed else framework.next_rng_key()
        shape = tuple(shape)
        return apply_op(
            lambda lg: jax.random.categorical(
                k, lg, axis=-1, shape=shape + lg.shape[:-1]),
            self.logits, _name='categorical_sample')

    def probs(self, value=None):
        p = apply_op(lambda lg: jax.nn.softmax(lg, axis=-1), self.logits,
                     _name='softmax')
        if value is None:
            return p
        return apply_op(
            lambda pv, idx: jnp.take_along_axis(
                pv, idx.astype(jnp.int32)[..., None], axis=-1)[..., 0],
            p, _as_t(value), _name='categorical_probs')

    def log_prob(self, value):
        def f(lg, idx):
            logp = jax.nn.log_softmax(lg, axis=-1)
            idx = idx.astype(jnp.int32).reshape(lg.shape[:-1])
            return jnp.take_along_axis(logp, idx[..., None],
                                       axis=-1)[..., 0]
        return apply_op(f, self.logits, _as_t(value),
                        _name='categorical_log_prob')

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return apply_op(f, self.logits, _name='categorical_entropy')

    def kl_divergence(self, other: 'Categorical'):
        def f(a, b):
            pa = jax.nn.log_softmax(a, axis=-1)
            pb = jax.nn.log_softmax(b, axis=-1)
            return jnp.sum(jnp.exp(pa) * (pa - pb), axis=-1)
        return apply_op(f, self.logits, other.logits,
                        _name='categorical_kl')


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _as_t(probs)

    def sample(self, shape=(), seed=0):
        k = jax.random.key(seed) if seed else framework.next_rng_key()
        shape = tuple(shape)
        return apply_op(
            lambda p: jax.random.bernoulli(
                k, p, shape + p.shape).astype(jnp.float32),
            self.probs, _name='bernoulli_sample')

    def log_prob(self, value):
        def f(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op(f, self.probs, _as_t(value),
                        _name='bernoulli_log_prob')

    def entropy(self):
        def f(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op(f, self.probs, _name='bernoulli_entropy')


def kl_divergence(p: Distribution, q: Distribution):
    """Dispatch on matched distribution types (upstream
    paddle.distribution.kl_divergence)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            f'kl_divergence({type(p).__name__}, {type(q).__name__}) '
            f'is not registered')
    if hasattr(p, 'kl_divergence'):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f'kl_divergence not implemented for {type(p).__name__}')
