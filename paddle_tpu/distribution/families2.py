"""Remaining upstream distribution families (upstream:
python/paddle/distribution/{binomial,cauchy,chi2,continuous_bernoulli,
multivariate_normal,lkj_cholesky}.py).

Same TPU-native contract as the rest of the zoo: densities/statistics are
pure jnp computations recorded on the tape via apply_op; sampling uses the
stateless threefry stream; rsample is provided where upstream has a
reparameterized path."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy import special as jsp

from ..tensor import Tensor, apply_op, to_jax


# imported at the END of distribution/__init__, after the base classes
# exist on the package module — so this direct import is not circular
from . import Distribution, Gamma, _as_t, _key, register_kl

class Binomial(Distribution):
    """Binomial(total_count n, probs p) (upstream
    distribution/binomial.py)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _as_t(total_count)
        self.probs = _as_t(probs)

    @property
    def mean(self):
        return apply_op(lambda n, p: n * p, self.total_count,
                        self.probs, _name='binomial_mean')

    @property
    def variance(self):
        return apply_op(lambda n, p: n * p * (1 - p), self.total_count,
                        self.probs, _name='binomial_var')

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(n, p):
            base = jnp.broadcast_shapes(n.shape, p.shape)
            return jax.random.binomial(k, n, p, shape=shape + base)
        return apply_op(f, self.total_count, self.probs,
                        _name='binomial_sample')

    def log_prob(self, value):
        def f(v, n, p):
            comb = (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1))
            return comb + jsp.xlogy(v, p) + jsp.xlog1py(n - v, -p)
        return apply_op(f, _as_t(value), self.total_count, self.probs,
                        _name='binomial_log_prob')

    def entropy(self):
        """Exact entropy by support summation (support is concrete:
        total_count is data, not a traced value)."""
        nmax = int(np.max(np.asarray(to_jax(self.total_count))))

        def f(n, p):
            ks = jnp.arange(nmax + 1, dtype=jnp.float32)
            kshape = ks.reshape((-1,) + (1,) * max(n.ndim, p.ndim))
            lp = (jsp.gammaln(n + 1) - jsp.gammaln(kshape + 1)
                  - jsp.gammaln(n - kshape + 1)
                  + jsp.xlogy(kshape, p) + jsp.xlog1py(n - kshape, -p))
            lp = jnp.where(kshape <= n, lp, -jnp.inf)
            return -jnp.sum(jnp.where(jnp.isfinite(lp),
                                      jnp.exp(lp) * lp, 0.0), axis=0)
        return apply_op(f, self.total_count, self.probs,
                        _name='binomial_entropy')

class Cauchy(Distribution):
    """Cauchy(loc, scale) (upstream distribution/cauchy.py). Mean and
    variance are undefined and raise, as upstream does."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    @property
    def mean(self):
        raise ValueError('Cauchy distribution has no mean')

    @property
    def variance(self):
        raise ValueError('Cauchy distribution has no variance')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(m, g):
            base = jnp.broadcast_shapes(m.shape, g.shape)
            return m + g * jax.random.cauchy(k, shape + base,
                                             jnp.float32)
        return apply_op(f, self.loc, self.scale, _name='cauchy_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, m, g):
            z = (v - m) / g
            return -math.log(math.pi) - jnp.log(g) - jnp.log1p(z * z)
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='cauchy_log_prob')

    def entropy(self):
        return apply_op(lambda g: jnp.log(4 * math.pi * g),
                        self.scale, _name='cauchy_entropy')

    def cdf(self, value):
        def f(v, m, g):
            return jnp.arctan((v - m) / g) / math.pi + 0.5
        return apply_op(f, _as_t(value), self.loc, self.scale,
                        _name='cauchy_cdf')

class Chi2(Gamma):
    """Chi-squared(df) = Gamma(df/2, rate=1/2) (upstream
    distribution/chi2.py). Inherits Gamma's sampling/density — and
    the registered Gamma-Gamma KL via MRO dispatch."""

    def __init__(self, df, name=None):
        df = _as_t(df)
        super().__init__(concentration=df * 0.5, rate=0.5)
        self.df = df

class ContinuousBernoulli(Distribution):
    """CB(λ) on [0,1] (upstream distribution/continuous_bernoulli.py;
    Loaiza-Ganem & Cunningham 2019). `lims` brackets the unstable
    region around λ=0.5 where the closed forms 0/0 — inside it a
    Taylor expansion is used, as upstream does."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _as_t(probs)
        self._lims = lims

    def _unstable(self, lam):
        lo, hi = self._lims
        return (lam > lo) & (lam < hi)

    def _log_norm(self, lam):
        """log C(λ), C = 2 atanh(1-2λ)/(1-2λ) for λ≠1/2, 2 at 1/2."""
        safe = jnp.where(self._unstable(lam), 0.25, lam)
        x = 1.0 - 2.0 * safe
        exact = jnp.log(2.0 * jnp.arctanh(x) / x)
        t = 1.0 - 2.0 * lam  # small inside lims
        taylor = math.log(2.0) + (t * t) / 3.0 + (t ** 4) * 2.0 / 15.0
        return jnp.where(self._unstable(lam), taylor, exact)

    @property
    def mean(self):
        def f(lam):
            safe = jnp.where(self._unstable(lam), 0.25, lam)
            exact = safe / (2.0 * safe - 1.0) \
                + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            t = lam - 0.5
            taylor = 0.5 + t / 3.0  # series about λ=1/2
            return jnp.where(self._unstable(lam), taylor, exact)
        return apply_op(f, self.probs, _name='cb_mean')

    @property
    def variance(self):
        def f(lam):
            safe = jnp.where(self._unstable(lam), 0.25, lam)
            x = 1.0 - 2.0 * safe
            at = jnp.arctanh(x)
            exact = safe * (safe - 1.0) / (x * x) + 1.0 / (4.0 * at * at)
            t = lam - 0.5
            taylor = 1.0 / 12.0 - (t * t) / 15.0
            return jnp.where(self._unstable(lam), taylor, exact)
        return apply_op(f, self.probs, _name='cb_var')

    def icdf(self, value):
        def f(u, lam):
            safe = jnp.where(self._unstable(lam), 0.25, lam)
            num = jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
            den = jnp.log(safe) - jnp.log1p(-safe)
            return jnp.where(self._unstable(lam), u, num / den)
        return apply_op(f, _as_t(value), self.probs, _name='cb_icdf')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(lam):
            u = jax.random.uniform(k, shape + lam.shape, jnp.float32)
            safe = jnp.where(self._unstable(lam), 0.25, lam)
            num = jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
            den = jnp.log(safe) - jnp.log1p(-safe)
            return jnp.where(self._unstable(lam), u, num / den)
        return apply_op(f, self.probs, _name='cb_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, lam):
            return (jsp.xlogy(v, lam) + jsp.xlog1py(1.0 - v, -lam)
                    + self._log_norm(lam))
        return apply_op(f, _as_t(value), self.probs,
                        _name='cb_log_prob')

    def entropy(self):
        def f(lam):
            safe = jnp.where(self._unstable(lam), 0.25, lam)
            exact_mean = safe / (2.0 * safe - 1.0) \
                + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            t = lam - 0.5
            mu = jnp.where(self._unstable(lam), 0.5 + t / 3.0,
                           exact_mean)
            return -(self._log_norm(lam) + jsp.xlogy(mu, lam)
                     + jsp.xlog1py(1.0 - mu, -lam))
        return apply_op(f, self.probs, _name='cb_entropy')

class MultivariateNormal(Distribution):
    """MVN(loc, covariance_matrix | precision_matrix | scale_tril)
    (upstream distribution/multivariate_normal.py). Internally
    parameterized by the Cholesky factor L — every density/sampling
    op is a triangular solve or matmul, which XLA maps onto the
    MXU."""

    def __init__(self, loc, covariance_matrix=None,
                 precision_matrix=None, scale_tril=None, name=None):
        self.loc = _as_t(loc)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError('pass exactly one of covariance_matrix, '
                             'precision_matrix, scale_tril')
        if scale_tril is not None:
            self.scale_tril = _as_t(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = apply_op(jnp.linalg.cholesky,
                                       _as_t(covariance_matrix),
                                       _name='mvn_chol')
        else:
            def inv_chol(prec):
                # prec = Lp Lpᵀ  ⇒  cov = Lp⁻ᵀ Lp⁻¹ (batched)
                lp = jnp.linalg.cholesky(prec)
                eye = jnp.broadcast_to(
                    jnp.eye(prec.shape[-1], dtype=prec.dtype), prec.shape)
                linv = jax.scipy.linalg.solve_triangular(
                    lp, eye, lower=True)
                cov = jnp.swapaxes(linv, -1, -2) @ linv
                return jnp.linalg.cholesky(cov)
            self.scale_tril = apply_op(inv_chol, _as_t(precision_matrix),
                                       _name='mvn_prec_chol')

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        return apply_op(lambda l: l @ jnp.swapaxes(l, -1, -2),
                        self.scale_tril, _name='mvn_cov')

    @property
    def variance(self):
        return apply_op(
            lambda l: jnp.sum(l * l, axis=-1), self.scale_tril,
            _name='mvn_var')

    def rsample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)

        def f(mu, l):
            d = l.shape[-1]
            base = jnp.broadcast_shapes(mu.shape[:-1], l.shape[:-2])
            eps = jax.random.normal(k, shape + base + (d,), jnp.float32)
            return mu + jnp.einsum('...ij,...j->...i', l, eps)
        return apply_op(f, self.loc, self.scale_tril,
                        _name='mvn_sample')

    sample = rsample

    def log_prob(self, value):
        def f(v, mu, l):
            d = l.shape[-1]
            diff = v - mu
            # solve_triangular does not broadcast batch dims — align them
            bshape = jnp.broadcast_shapes(diff.shape[:-1], l.shape[:-2])
            diff = jnp.broadcast_to(diff, bshape + diff.shape[-1:])
            lb = jnp.broadcast_to(l, bshape + l.shape[-2:])
            z = jax.scipy.linalg.solve_triangular(
                lb, diff[..., None], lower=True)[..., 0]
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(l, axis1=-2, axis2=-1)), axis=-1)
            return (-0.5 * jnp.sum(z * z, axis=-1) - half_logdet
                    - 0.5 * d * math.log(2 * math.pi))
        return apply_op(f, _as_t(value), self.loc, self.scale_tril,
                        _name='mvn_log_prob')

    def entropy(self):
        def f(l):
            d = l.shape[-1]
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(l, axis1=-2, axis2=-1)), axis=-1)
            return 0.5 * d * (1.0 + math.log(2 * math.pi)) + half_logdet
        return apply_op(f, self.scale_tril, _name='mvn_entropy')

class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices
    (upstream distribution/lkj_cholesky.py). Sampling uses the onion
    construction — d-1 Beta draws plus points on spheres — expressed
    as one batched computation (no python-per-row device work)."""

    def __init__(self, dim, concentration=1.0,
                 sample_method='onion', name=None):
        if dim < 2:
            raise ValueError('LKJCholesky needs dim >= 2')
        if sample_method not in ('onion', 'cvine'):
            raise ValueError(f'unknown sample_method {sample_method!r}')
        self.dim = int(dim)
        self.concentration = _as_t(concentration)
        self.sample_method = sample_method

    def sample(self, shape=(), seed=0):
        k = _key(seed)
        shape = tuple(shape)
        d = self.dim
        f = self._sample_onion if self.sample_method == 'onion' \
            else self._sample_cvine
        return apply_op(lambda conc: f(conc, k, shape),
                        self.concentration, _name='lkj_sample')

    def _sample_onion(self, conc, k, shape):
        d = self.dim
        batch = shape + conc.shape
        # onion: row i (1-based, i>=1) needs y~Beta(i/2, off_i)
        # with offset walking down from conc + (d-2)/2
        ks = jax.random.split(k, 2)
        i = jnp.arange(1, d, dtype=jnp.float32)
        offs = conc[..., None] + (d - 2) / 2.0 - (i - 1) / 2.0
        y = jax.random.beta(ks[0], i / 2.0, offs,
                            batch + (d - 1,))
        z = jax.random.normal(ks[1], batch + (d - 1, d),
                              jnp.float32)
        # unit vectors on the first i coords of each row
        cols = jnp.arange(d)[None, :]
        rowmask = cols < i[:, None]
        z = jnp.where(rowmask, z, 0.0)
        u = z / jnp.maximum(
            jnp.linalg.norm(z, axis=-1, keepdims=True), 1e-20)
        w = jnp.sqrt(y)[..., None] * u
        low = jnp.zeros(batch + (d, d), jnp.float32)
        low = low.at[..., 1:, :].set(w)
        diag = jnp.concatenate(
            [jnp.ones(batch + (1,), jnp.float32),
             jnp.sqrt(1.0 - y)], axis=-1)
        eye = jnp.eye(d, dtype=jnp.float32)
        return low * (1.0 - eye) + diag[..., None] * eye

    def _sample_cvine(self, conc, k, shape):
        """C-vine (Lewandowski et al. 2009 §3.1): partial correlations
        z_ij ~ 2·Beta(a_j, a_j) − 1 with a_j = conc + (d−2−j)/2 by tree
        level j, mapped to the Cholesky factor by the recursive
        sqrt(1−z²) cumulative product — here one batched cumprod."""
        d = self.dim
        batch = shape + conc.shape
        j = jnp.arange(d, dtype=jnp.float32)
        # level-wise Beta parameter, aligned to the trailing (row, level)
        # axes of the draw shape
        a = conc[..., None, None] + (d - 2.0 - j) / 2.0
        y = jax.random.beta(k, a, a, batch + (d - 1, d))
        z = 2.0 * y - 1.0  # partial correlations in (-1, 1)
        rows = jnp.arange(1, d)[:, None]
        cols = jnp.arange(d)[None, :]
        mask = cols < rows  # row i uses levels j = 0..i-1
        z = jnp.where(mask, z, 0.0)
        # cum_ij = prod_{k<j} sqrt(1 - z_ik^2)  (exclusive cumprod)
        s = jnp.sqrt(jnp.clip(1.0 - z * z, 1e-20, None))
        cum = jnp.cumprod(jnp.where(mask, s, 1.0), axis=-1)
        excl = jnp.concatenate(
            [jnp.ones(batch + (d - 1, 1), jnp.float32),
             cum[..., :-1]], axis=-1)
        w = jnp.where(mask, z * excl, 0.0)
        # L_ii = prod_{k<i} sqrt(1 - z_ik^2) = cum at the last used level
        diag_low = jnp.take_along_axis(
            cum, jnp.broadcast_to(rows - 1, batch + (d - 1, 1)).astype(int),
            axis=-1)[..., 0]
        low = jnp.zeros(batch + (d, d), jnp.float32)
        low = low.at[..., 1:, :].set(w)
        diag = jnp.concatenate(
            [jnp.ones(batch + (1,), jnp.float32), diag_low], axis=-1)
        eye = jnp.eye(d, dtype=jnp.float32)
        return low * (1.0 - eye) + diag[..., None] * eye

    def log_prob(self, value):
        d = self.dim

        def f(l, conc):
            i = jnp.arange(1, d, dtype=jnp.float32)
            order = 2.0 * (conc[..., None] - 1.0) + d - i - 1.0
            diags = jnp.diagonal(l, axis1=-2, axis2=-1)[..., 1:]
            unnorm = jnp.sum(order * jnp.log(diags), axis=-1)
            # normalization constant (LKJ 2009 p.1999, Cholesky-factor
            # density): ½(d−1)·log π + log Γ_{d−1}(α − ½) − (d−1)·log Γ(α)
            # with α = conc + (d−1)/2 and Γ_p the multivariate gamma
            dm1 = d - 1
            alpha = conc + 0.5 * dm1
            j = jnp.arange(1, dm1 + 1, dtype=jnp.float32)
            mvlg = dm1 * (dm1 - 1) / 4.0 * math.log(math.pi) + jnp.sum(
                jsp.gammaln(alpha[..., None] - 0.5 + (1.0 - j) / 2.0),
                axis=-1)
            norm = (0.5 * dm1 * math.log(math.pi) + mvlg
                    - dm1 * jsp.gammaln(alpha))
            return unnorm - norm
        return apply_op(f, _as_t(value), self.concentration,
                        _name='lkj_log_prob')

# closed-form KLs for the new pairs
@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    def f(m0, g0, m1, g1):
        return jnp.log(((g0 + g1) ** 2 + (m0 - m1) ** 2)
                       / (4.0 * g0 * g1))
    return apply_op(f, p.loc, p.scale, q.loc, q.scale,
                    _name='kl_cauchy')

@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def f(mu0, l0, mu1, l1):
        d = l0.shape[-1]
        # align batch dims: solve_triangular does not broadcast
        bshape = jnp.broadcast_shapes(l0.shape[:-2], l1.shape[:-2],
                                      mu0.shape[:-1], mu1.shape[:-1])
        l0 = jnp.broadcast_to(l0, bshape + l0.shape[-2:])
        l1 = jnp.broadcast_to(l1, bshape + l1.shape[-2:])
        diff = jnp.broadcast_to(mu1 - mu0, bshape + (d,))
        half0 = jnp.sum(jnp.log(jnp.diagonal(l0, axis1=-2, axis2=-1)),
                        axis=-1)
        half1 = jnp.sum(jnp.log(jnp.diagonal(l1, axis1=-2, axis2=-1)),
                        axis=-1)
        m = jax.scipy.linalg.solve_triangular(l1, l0, lower=True)
        tr = jnp.sum(m * m, axis=(-2, -1))
        z = jax.scipy.linalg.solve_triangular(
            l1, diff[..., None], lower=True)[..., 0]
        return half1 - half0 + 0.5 * (tr + jnp.sum(z * z, axis=-1) - d)
    return apply_op(f, p.loc, p.scale_tril, q.loc, q.scale_tril,
                    _name='kl_mvn')

