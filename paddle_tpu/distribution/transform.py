"""paddle.distribution.transform (upstream: python/paddle/distribution/
transform.py) — invertible maps with log-det-Jacobians, the building
blocks of TransformedDistribution. Pure jnp computations recorded on the
tape via apply_op so everything stays differentiable."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor, apply_op, to_jax

__all__ = [
    'Transform', 'AffineTransform', 'ExpTransform', 'SigmoidTransform',
    'TanhTransform', 'PowerTransform', 'AbsTransform', 'ChainTransform',
]


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(to_jax(x),
                                                              jnp.float32))


class Transform:
    """Bijective map y = f(x). Subclasses implement `_forward`,
    `_inverse`, `_forward_log_det_jacobian` as pure jnp functions."""

    def forward(self, x):
        return apply_op(self._forward, _as_t(x),
                        _name=type(self).__name__ + '_fwd')

    def inverse(self, y):
        return apply_op(self._inverse, _as_t(y),
                        _name=type(self).__name__ + '_inv')

    def forward_log_det_jacobian(self, x):
        return apply_op(self._forward_log_det_jacobian, _as_t(x),
                        _name=type(self).__name__ + '_fldj')

    def inverse_log_det_jacobian(self, y):
        # d/dy f^{-1} = 1 / f'(f^{-1}(y))
        x = self.inverse(y)
        return apply_op(lambda v: -self._forward_log_det_jacobian(v), x,
                        _name=type(self).__name__ + '_ildj')

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    def forward(self, x):
        return apply_op(lambda v, l, s: l + s * v, _as_t(x), self.loc,
                        self.scale, _name='affine_fwd')

    def inverse(self, y):
        return apply_op(lambda v, l, s: (v - l) / s, _as_t(y), self.loc,
                        self.scale, _name='affine_inv')

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v, s: jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                          jnp.broadcast_shapes(v.shape,
                                                               s.shape)),
            _as_t(x), self.scale, _name='affine_fldj')

    def inverse_log_det_jacobian(self, y):
        return apply_op(
            lambda v, s: jnp.broadcast_to(-jnp.log(jnp.abs(s)),
                                          jnp.broadcast_shapes(v.shape,
                                                               s.shape)),
            _as_t(y), self.scale, _name='affine_ildj')


class ExpTransform(Transform):
    """y = exp(x)."""

    @staticmethod
    def _forward(v):
        return jnp.exp(v)

    @staticmethod
    def _inverse(v):
        return jnp.log(v)

    @staticmethod
    def _forward_log_det_jacobian(v):
        return v


class SigmoidTransform(Transform):
    """y = sigmoid(x)."""

    @staticmethod
    def _forward(v):
        return 1.0 / (1.0 + jnp.exp(-v))

    @staticmethod
    def _inverse(v):
        return jnp.log(v) - jnp.log1p(-v)

    @staticmethod
    def _forward_log_det_jacobian(v):
        # log sigmoid'(x) = log σ(x) + log σ(-x), stably via softplus
        return -jnp.logaddexp(0.0, -v) - jnp.logaddexp(0.0, v)


class TanhTransform(Transform):
    """y = tanh(x)."""

    @staticmethod
    def _forward(v):
        return jnp.tanh(v)

    @staticmethod
    def _inverse(v):
        return jnp.arctanh(v)

    @staticmethod
    def _forward_log_det_jacobian(v):
        # log(1 - tanh(x)^2) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - v - jnp.logaddexp(0.0, -2.0 * v))


class PowerTransform(Transform):
    """y = x ** power (x > 0)."""

    def __init__(self, power):
        self.power = _as_t(power)

    def forward(self, x):
        return apply_op(lambda v, p: jnp.power(v, p), _as_t(x), self.power,
                        _name='power_fwd')

    def inverse(self, y):
        return apply_op(lambda v, p: jnp.power(v, 1.0 / p), _as_t(y),
                        self.power, _name='power_inv')

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v, p: jnp.log(jnp.abs(p)) + (p - 1.0) * jnp.log(v),
            _as_t(x), self.power, _name='power_fldj')

    def inverse_log_det_jacobian(self, y):
        x = self.inverse(y)
        return apply_op(
            lambda v, p: -(jnp.log(jnp.abs(p)) + (p - 1.0) * jnp.log(v)),
            x, self.power, _name='power_ildj')


class AbsTransform(Transform):
    """y = |x| — not bijective; inverse returns the positive branch
    (upstream AbsTransform does the same)."""

    @staticmethod
    def _forward(v):
        return jnp.abs(v)

    @staticmethod
    def _inverse(v):
        return v

    @staticmethod
    def _forward_log_det_jacobian(v):
        return jnp.zeros_like(v)


class ChainTransform(Transform):
    """Composition: y = fN(...f1(x))."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ld = t.forward_log_det_jacobian(x)
            total = ld if total is None else total + ld
            x = t.forward(x)
        return total

    def inverse_log_det_jacobian(self, y):
        total = None
        for t in reversed(self.transforms):
            ld = t.inverse_log_det_jacobian(y)
            total = ld if total is None else total + ld
            y = t.inverse(y)
        return total
