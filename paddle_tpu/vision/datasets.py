"""paddle.vision.datasets (upstream: python/paddle/vision/datasets/).

Offline build: `download=True` is rejected (zero egress). Each dataset
reads the standard on-disk format when a local copy exists, and exposes
`mode='synthetic'`-style fallback via `backend='synthetic'` — a
deterministic generated stand-in with the real shapes/dtypes so training
pipelines and tests run without the archives.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io import Dataset


class _SyntheticImages(Dataset):
    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.labels = rng.randint(0, num_classes, n).astype(np.int64)
        # class-dependent mean so models can actually fit the data
        base = rng.rand(num_classes, *shape).astype(np.float32)
        noise = rng.rand(n, *shape).astype(np.float32) * 0.3
        self.images = (base[self.labels] * 0.7 + noise)
        self.images = (self.images * 255).astype(np.uint8)
        self.transform = transform

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]

    def __len__(self):
        return len(self.images)


def _reject_download(download):
    if download:
        raise RuntimeError(
            'downloads are disabled in this offline build; place the '
            'dataset files locally and pass image_path/data_file, or use '
            'backend="synthetic"')


class MNIST(Dataset):
    """MNIST idx-format reader with synthetic fallback."""

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform: Optional[Callable] = None, download=False,
                 backend=None):
        _reject_download(download)
        self.transform = transform
        if backend == 'synthetic' or image_path is None:
            n = 256 if mode == 'train' else 64
            self._syn = _SyntheticImages(n, (28, 28), 10, transform)
            self.images, self.labels = None, None
            return
        self._syn = None
        with gzip.open(image_path, 'rb') if image_path.endswith('.gz') \
                else open(image_path, 'rb') as f:
            magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
            self.images = np.frombuffer(
                f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, 'rb') if label_path.endswith('.gz') \
                else open(label_path, 'rb') as f:
            struct.unpack('>II', f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8) \
                .astype(np.int64)

    def __getitem__(self, i):
        if self._syn is not None:
            return self._syn[i]
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]

    def __len__(self):
        return len(self._syn) if self._syn is not None else len(self.images)


class Cifar10(Dataset):
    """CIFAR-10 python-pickle format reader with synthetic fallback."""

    _num_classes = 10
    _label_key = b'labels'

    def _members(self, mode):
        return [f'data_batch_{i}' for i in range(1, 6)] \
            if mode == 'train' else ['test_batch']

    def __init__(self, data_file=None, mode='train',
                 transform: Optional[Callable] = None, download=False,
                 backend=None):
        _reject_download(download)
        self.transform = transform
        if backend == 'synthetic' or data_file is None:
            n = 256 if mode == 'train' else 64
            self._syn = _SyntheticImages(n, (32, 32, 3),
                                         self._num_classes, transform,
                                         seed=1)
            return
        self._syn = None
        images, labels = [], []
        names = self._members(mode)
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                if os.path.basename(member.name) in names:
                    d = pickle.load(tf.extractfile(member),
                                    encoding='bytes')
                    images.append(np.asarray(d[b'data']))
                    labels.extend(d[self._label_key])
        if not images:
            raise FileNotFoundError(
                f'no members {names} found in {data_file!r}')
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, i):
        if self._syn is not None:
            return self._syn[i]
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]

    def __len__(self):
        return len(self._syn) if self._syn is not None else len(self.images)


class Cifar100(Cifar10):
    """CIFAR-100: 'train'/'test' archive members, fine labels, 100
    classes."""

    _num_classes = 100
    _label_key = b'fine_labels'

    def _members(self, mode):
        return ['train'] if mode == 'train' else ['test']


IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.pgm',
                  '.tif', '.tiff', '.webp')


def _scan_files(root, extensions, is_valid_file):
    """Deterministic recursive file scan shared by DatasetFolder and
    ImageFolder; default filter = extension allowlist."""
    if is_valid_file is None:
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))

        def is_valid_file(p):
            return p.lower().endswith(exts)
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fn in sorted(files):
            p = os.path.join(dirpath, fn)
            if is_valid_file(p):
                out.append(p)
    return out


def _default_loader(path):
    from . import image as _image
    return _image.image_load(path)


class DatasetFolder(Dataset):
    """Generic folder-of-class-subdirs dataset (upstream
    paddle.vision.datasets.DatasetFolder): root/class_x/xxx.ext -> label
    by sorted class-dir order."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f'no class folders under {root!r}')
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for p in _scan_files(os.path.join(root, c), extensions,
                                 is_valid_file):
                self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f'no valid files under {root!r}')

    def __getitem__(self, i):
        path, label = self.samples[i]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (unlabeled) image folder (upstream
    paddle.vision.datasets.ImageFolder): returns [img] per sample."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        self.samples = _scan_files(root, extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError(f'no valid files under {root!r}')

    def __getitem__(self, i):
        img = self.loader(self.samples[i])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
