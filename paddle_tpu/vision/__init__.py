"""paddle.vision — model zoo, transforms, datasets, ops."""
from . import datasets, image, models, ops, transforms  # noqa: F401
from .image import (get_image_backend, image_load,  # noqa: F401
                    set_image_backend)
from .models import (AlexNet, DenseNet, GoogLeNet,  # noqa: F401
                     InceptionV3, LeNet, MobileNetV1, MobileNetV2,
                     MobileNetV3, ResNet, ShuffleNetV2, SqueezeNet, VGG,
                     alexnet, densenet121, densenet161, densenet169,
                     densenet201, googlenet, inception_v3, mobilenet_v1,
                     mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small,
                     resnet18, resnet34, resnet50, resnet101, resnet152,
                     resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
                     resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
                     shufflenet_v2_x0_25, shufflenet_v2_x0_5,
                     shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                     shufflenet_v2_x2_0, squeezenet1_0, squeezenet1_1,
                     vgg16, wide_resnet50_2, wide_resnet101_2)
