"""paddle.vision — model zoo, transforms, datasets."""
from . import datasets, models, transforms  # noqa: F401
from .models import (LeNet, MobileNetV2, ResNet, VGG,  # noqa: F401
                     mobilenet_v2, resnet18, resnet34, resnet50, resnet101,
                     resnet152, vgg16)
