"""paddle.vision.ops (upstream: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, deform_conv2d, box_coder).

TPU-native design notes:
- `nms` computes the full IoU matrix on device (one [N,N] batched op —
  MXU/VPU friendly) and runs the inherently-sequential suppression scan
  in a `lax.fori_loop`; the dynamic-size index list materializes on
  host (eager op — dynamic shapes cannot live under jit anyway).
- `roi_align` / `deform_conv2d` are gather+bilinear formulations: XLA
  lowers the gathers and the interpolation arithmetic fuses; there is
  no CUDA-style per-thread kernel to port.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op, to_jax

__all__ = ['nms', 'roi_align', 'roi_pool', 'deform_conv2d', 'box_iou',
           'box_coder']


def _iou_matrix(boxes):
    """[N,4] xyxy -> [N,N] IoU."""
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_iou(boxes1, boxes2) -> Tensor:
    """Pairwise IoU between two box sets ([N,4] x [M,4] -> [N,M])."""
    def f(a, b):
        both = jnp.concatenate([a, b], axis=0)
        return _iou_matrix(both)[:a.shape[0], a.shape[0]:]
    return apply_op(f, boxes1, boxes2, _name='box_iou')


@jax.jit
def _nms_keep(boxes, scores, iou_threshold):
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes[order])

    def body(i, keep):
        # suppressed if any higher-scoring kept box overlaps > threshold
        over = (iou[i] > iou_threshold) & keep & \
            (jnp.arange(keep.shape[0]) < i)
        return keep.at[i].set(~jnp.any(over))

    keep = jax.lax.fori_loop(0, boxes.shape[0], body,
                             jnp.ones(boxes.shape[0], bool))
    return order, keep


def nms(boxes, scores=None, iou_threshold=0.3, score_threshold=None,
        category_idxs=None, categories=None, top_k=None):
    """Hard-NMS; returns kept indices ordered by descending score.
    With `category_idxs`, suppression is per category (multiclass NMS)."""
    bv = jnp.asarray(to_jax(boxes), jnp.float32)
    sv = jnp.asarray(to_jax(scores), jnp.float32) if scores is not None \
        else jnp.zeros(bv.shape[0])
    if score_threshold is not None:
        valid = np.asarray(sv) >= score_threshold
    else:
        valid = np.ones(bv.shape[0], bool)
    if category_idxs is not None:
        # offset boxes per category so cross-category IoU is zero
        cv = jnp.asarray(to_jax(category_idxs))
        span = (bv.max() - bv.min()) + 1.0
        bv = bv + (cv[:, None].astype(jnp.float32) * span)
    order, keep = _nms_keep(bv, sv, jnp.float32(iou_threshold))
    order, keep = np.asarray(order), np.asarray(keep)
    kept = order[keep & valid[order]]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int32))


def _bilinear(feat, y, x):
    """feat [C,H,W]; y/x sample grids of equal shape -> [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(y - y0, 0, 1)
    wx = jnp.clip(x - x0, 0, 1)
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (Mask R-CNN): average of bilinear samples per output bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(xv, rois, nper):
        xv = xv.astype(jnp.float32)
        rois = rois.astype(jnp.float32)
        img_of_roi = jnp.repeat(jnp.arange(nper.shape[0]), nper,
                                total_repeat_length=rois.shape[0])
        off = 0.5 if aligned else 0.0
        ratio = sampling_ratio if sampling_ratio > 0 else 2

        def one(roi, img_idx):
            x1, y1, x2, y2 = roi * spatial_scale
            rw = jnp.maximum(x2 - x1, 1e-4)
            rh = jnp.maximum(y2 - y1, 1e-4)
            bin_h, bin_w = rh / ph, rw / pw
            iy = jnp.arange(ph)[:, None, None, None]
            ix = jnp.arange(pw)[None, :, None, None]
            sy = jnp.arange(ratio)[None, None, :, None]
            sx = jnp.arange(ratio)[None, None, None, :]
            yy = y1 - off + (iy + (sy + 0.5) / ratio) * bin_h
            xx = x1 - off + (ix + (sx + 0.5) / ratio) * bin_w
            samp = _bilinear(xv[img_idx], yy, xx)  # [C,ph,pw,r,r]
            return samp.mean(axis=(-1, -2))

        return jax.vmap(one)(rois, img_of_roi)

    return apply_op(f, x, boxes, boxes_num, _name='roi_align')


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """RoIPool (Fast R-CNN): hard max over each quantized bin. Static
    shapes for XLA: every bin gathers a fixed 8x8 grid of rounded
    integer cells (exact for bins up to 8px; a dense approximation of
    the per-bin max beyond that)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(xv, rois, nper):
        xv = xv.astype(jnp.float32)
        rois = rois.astype(jnp.float32)
        H, W = xv.shape[-2:]
        img_of_roi = jnp.repeat(jnp.arange(nper.shape[0]), nper,
                                total_repeat_length=rois.shape[0])
        ratio = 8

        def one(roi, img_idx):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            iy = jnp.arange(ph)[:, None, None, None]
            ix = jnp.arange(pw)[None, :, None, None]
            sy = jnp.arange(ratio)[None, None, :, None]
            sx = jnp.arange(ratio)[None, None, None, :]
            yy = jnp.round(y1 + (iy + sy / (ratio - 1)) * (rh / ph))
            xx = jnp.round(x1 + (ix + sx / (ratio - 1)) * (rw / pw))
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            samp = xv[img_idx][:, yi, xi]  # [C,ph,pw,r,r]
            return samp.max(axis=(-1, -2))

        return jax.vmap(one)(rois, img_of_roi)

    return apply_op(f, x, boxes, boxes_num, _name='roi_pool')


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 (dai et al.): bilinear-sample the input at
    offset-shifted taps, then a dense matmul with the kernel — the
    gather feeds the MXU instead of a custom CUDA kernel."""
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError('deform_conv2d supports groups=1, '
                                  'deformable_groups=1')
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    has_mask, has_bias = mask is not None, bias is not None

    def f(xv, ov, wv, *rest):
        mv = rest[0] if has_mask else None
        bv = rest[1 if has_mask else 0] if has_bias else None
        xv = xv.astype(jnp.float32)
        N, C, H, W = xv.shape
        out_c, _, kh, kw = wv.shape
        xp = jnp.pad(xv, ((0, 0), (0, 0), (padding[0], padding[0]),
                          (padding[1], padding[1])))
        Hp, Wp = xp.shape[-2:]
        Ho = (Hp - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1
        Wo = (Wp - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1
        oy = ov[:, 0::2].reshape(N, kh, kw, Ho, Wo)
        ox = ov[:, 1::2].reshape(N, kh, kw, Ho, Wo)
        base_y = (jnp.arange(Ho) * stride[0])[None, None, :, None] \
            + (jnp.arange(kh) * dilation[0])[:, None, None, None]
        base_x = (jnp.arange(Wo) * stride[1])[None, None, None, :] \
            + (jnp.arange(kw) * dilation[1])[None, :, None, None]
        yy = base_y + oy  # [N,kh,kw,Ho,Wo]
        xx = base_x + ox

        def sample_img(img, y, x):
            return _bilinear(img, y, x)  # [C,kh,kw,Ho,Wo]

        cols = jax.vmap(sample_img)(xp, yy, xx)
        if has_mask:
            cols = cols * mv.reshape(N, 1, kh, kw, Ho, Wo)
        cols = cols.reshape(N, C * kh * kw, Ho * Wo)
        out = jnp.einsum('ok,nkp->nop', wv.reshape(out_c, -1), cols)
        out = out.reshape(N, out_c, Ho, Wo)
        if has_bias:
            out = out + bv.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op(f, *args, _name='deform_conv2d')


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True):
    """Encode/decode boxes against priors (SSD-style)."""
    def f(pb, pbv, tb):
        pb, pbv, tb = (v.astype(jnp.float32) for v in (pb, pbv, tb))
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == 'encode_center_size':
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            return jnp.stack([
                (tcx - pcx) / pw / pbv[:, 0],
                (tcy - pcy) / ph / pbv[:, 1],
                jnp.log(tw / pw) / pbv[:, 2],
                jnp.log(th / ph) / pbv[:, 3]], axis=1)
        # decode_center_size
        dcx = tb[:, 0] * pbv[:, 0] * pw + pcx
        dcy = tb[:, 1] * pbv[:, 1] * ph + pcy
        dw = jnp.exp(tb[:, 2] * pbv[:, 2]) * pw
        dh = jnp.exp(tb[:, 3] * pbv[:, 3]) * ph
        return jnp.stack([dcx - dw * 0.5 + norm * 0.5,
                          dcy - dh * 0.5 + norm * 0.5,
                          dcx + dw * 0.5 - norm * 0.5,
                          dcy + dh * 0.5 - norm * 0.5], axis=1)

    return apply_op(f, prior_box, prior_box_var, target_box,
                    _name='box_coder')


from ..nn.layer import Layer as _Layer


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d (upstream: paddle.vision.ops.DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + ks, attr=weight_attr)
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self.stride,
            padding=self.padding, dilation=self.dilation,
            deformable_groups=self.deformable_groups, groups=self.groups,
            mask=mask)


__all__.append('DeformConv2D')
