"""Extended vision zoo (upstream: python/paddle/vision/models/ —
alexnet.py, squeezenet.py, densenet.py, googlenet.py, inceptionv3.py,
shufflenetv2.py, mobilenetv1.py, mobilenetv3.py).

Same TPU note as models.py: convs lower to XLA conv_general_dilated on
the MXU; NCHW kept for API parity. `pretrained=True` is rejected
(offline build) by every factory, matching models.py's ResNet."""
from __future__ import annotations

from typing import List

from .. import nn
from ..nn import functional as F
from ..ops.manipulation import concat as paddle_concat
from ..tensor import Tensor, apply_op


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError('pretrained weights are unavailable offline; '
                         'load a local state_dict instead')


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1, act='relu'):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act == 'relu':
        layers.append(nn.ReLU())
    elif act == 'hardswish':
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


# ---------------------------------------------------------------------------
# AlexNet
# ---------------------------------------------------------------------------

class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        return self.classifier(self.avgpool(self.features(x)).flatten(1))


def alexnet(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return AlexNet(**kw)


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------

class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                     nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return paddle_concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version='1.0', num_classes=1000, dropout=0.5):
        super().__init__()
        if version == '1.0':
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:  # 1.1: pools moved earlier, smaller stem
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        return self.classifier(self.features(x)).flatten(1)


def squeezenet1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return SqueezeNet('1.0', **kw)


def squeezenet1_1(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return SqueezeNet('1.1', **kw)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)

    def forward(self, x):
        out = self.conv1(F.relu(self.norm1(x)))
        out = self.conv2(F.relu(self.norm2(out)))
        return paddle_concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(F.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers_cfg: List[int], growth=32, num_init=64,
                 bn_size=4, num_classes=1000):
        super().__init__()
        feats = [nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(num_init), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = num_init
        for i, n in enumerate(layers_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size))
                c += growth
            if i != len(layers_cfg) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        return self.classifier(self.avgpool(self.features(x)).flatten(1))


_DENSENET_CFG = {121: ([6, 12, 24, 16], 32, 64),
                 161: ([6, 12, 36, 24], 48, 96),
                 169: ([6, 12, 32, 32], 32, 64),
                 201: ([6, 12, 48, 32], 32, 64)}


def _densenet(depth, pretrained, **kw):
    _no_pretrained(pretrained)
    cfg, growth, init = _DENSENET_CFG[depth]
    return DenseNet(cfg, growth=growth, num_init=init, **kw)


def densenet121(pretrained=False, **kw):
    return _densenet(121, pretrained, **kw)


def densenet161(pretrained=False, **kw):
    return _densenet(161, pretrained, **kw)


def densenet169(pretrained=False, **kw):
    return _densenet(169, pretrained, **kw)


def densenet201(pretrained=False, **kw):
    return _densenet(201, pretrained, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet (Inception v1)
# ---------------------------------------------------------------------------

class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_bn(in_c, c1, 1)
        self.b2 = nn.Sequential(_conv_bn(in_c, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_bn(in_c, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                _conv_bn(in_c, proj, 1))

    def forward(self, x):
        return paddle_concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                        axis=1)


class _GoogLeNetAux(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _conv_bn(in_c, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.fc2 = nn.Linear(1024, num_classes)
        self.dropout = nn.Dropout(0.7)

    def forward(self, x):
        x = self.conv(self.pool(x)).flatten(1)
        return self.fc2(self.dropout(F.relu(self.fc1(x))))


class GoogLeNet(nn.Layer):
    """Returns (out, aux1, aux2) like the upstream model."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)
        self.aux1 = _GoogLeNetAux(512, num_classes)
        self.aux2 = _GoogLeNetAux(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        a1 = self.aux1(x)
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x)
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        out = self.fc(self.dropout(self.avgpool(x).flatten(1)))
        return out, a1, a2


def googlenet(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
# Inception v3
# ---------------------------------------------------------------------------

class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_feat):
        super().__init__()
        self.b1 = _conv_bn(in_c, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(in_c, 48, 1),
                                _conv_bn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv_bn(in_c, 64, 1),
                                _conv_bn(64, 96, 3, padding=1),
                                _conv_bn(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, pool_feat, 1))

    def forward(self, x):
        return paddle_concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                        axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _conv_bn(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv_bn(in_c, 64, 1),
                                 _conv_bn(64, 96, 3, padding=1),
                                 _conv_bn(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle_concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _conv_bn(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, 192, 1))

    def forward(self, x):
        return paddle_concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                        axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_conv_bn(in_c, 192, 1),
                                _conv_bn(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _conv_bn(in_c, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle_concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _conv_bn(in_c, 320, 1)
        self.b3_stem = _conv_bn(in_c, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_conv_bn(in_c, 448, 1),
                                      _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return paddle_concat(
            [self.b1(x),
             paddle_concat([self.b3_a(s), self.b3_b(s)], axis=1),
             paddle_concat([self.b3d_a(d), self.b3d_b(d)], axis=1),
             self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        return self.fc(self.dropout(self.avgpool(x).flatten(1)))


def inception_v3(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return InceptionV3(**kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------

def _channel_shuffle(x, groups):
    def f(v):
        b, c, h, w = v.shape
        return v.reshape(b, groups, c // groups, h, w) \
            .swapaxes(1, 2).reshape(b, c, h, w)
    return apply_op(f, x, _name='channel_shuffle')


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                _conv_bn(in_c, branch_c, 1))
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            _conv_bn(b2_in, branch_c, 1),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            _conv_bn(branch_c, branch_c, 1))

    def forward(self, x):
        if self.stride == 1:
            x1 = apply_op(lambda v: v[:, :v.shape[1] // 2], x,
                          _name='split_lo')
            x2 = apply_op(lambda v: v[:, v.shape[1] // 2:], x,
                          _name='split_hi')
            out = paddle_concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle_concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    '0.25': ([4, 8, 4], [24, 24, 48, 96, 512]),
    '0.5': ([4, 8, 4], [24, 48, 96, 192, 1024]),
    '1.0': ([4, 8, 4], [24, 116, 232, 464, 1024]),
    '1.5': ([4, 8, 4], [24, 176, 352, 704, 1024]),
    '2.0': ([4, 8, 4], [24, 244, 488, 976, 2048]),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale='1.0', num_classes=1000, act='relu'):
        super().__init__()
        repeats, channels = _SHUFFLE_CFG[str(scale)]
        self.stem = nn.Sequential(
            _conv_bn(3, channels[0], 3, stride=2, padding=1),
            nn.MaxPool2D(3, 2, padding=1))
        stages = []
        in_c = channels[0]
        for i, reps in enumerate(repeats):
            out_c = channels[i + 1]
            stages.append(_ShuffleUnit(in_c, out_c, 2))
            for _ in range(reps - 1):
                stages.append(_ShuffleUnit(out_c, out_c, 1))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.tail = _conv_bn(in_c, channels[-1], 1)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.tail(self.stages(self.stem(x)))
        return self.fc(self.avgpool(x).flatten(1))


def shufflenet_v2_x0_25(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2('0.25', **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2('0.5', **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2('1.0', **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2('1.5', **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return ShuffleNetV2('2.0', **kw)


# ---------------------------------------------------------------------------
# MobileNetV1 / MobileNetV3
# ---------------------------------------------------------------------------

class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()

        def c(ch):
            return max(8, int(ch * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 \
            + [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        for in_c, out_c, s in cfg:
            layers += [
                nn.Conv2D(c(in_c), c(in_c), 3, stride=s, padding=1,
                          groups=c(in_c), bias_attr=False),
                nn.BatchNorm2D(c(in_c)), nn.ReLU(),
                _conv_bn(c(in_c), c(out_c), 1)]
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        return self.fc(self.avgpool(self.features(x)).flatten(1))


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kw)


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(c, c // r, 1)
        self.fc2 = nn.Conv2D(c // r, c, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.hardsigmoid(self.fc2(F.relu(self.fc1(s))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_conv_bn(in_c, exp, 1, act=act))
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                             groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp),
                   nn.Hardswish() if act == 'hardswish' else nn.ReLU()]
        if se:
            layers.append(_SqueezeExcite(exp))
        layers.append(_conv_bn(exp, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return out + x if self.use_res else out


_MBV3_SMALL = [  # k, exp, out, se, act, stride
    (3, 16, 16, True, 'relu', 2), (3, 72, 24, False, 'relu', 2),
    (3, 88, 24, False, 'relu', 1), (5, 96, 40, True, 'hardswish', 2),
    (5, 240, 40, True, 'hardswish', 1), (5, 240, 40, True, 'hardswish', 1),
    (5, 120, 48, True, 'hardswish', 1), (5, 144, 48, True, 'hardswish', 1),
    (5, 288, 96, True, 'hardswish', 2), (5, 576, 96, True, 'hardswish', 1),
    (5, 576, 96, True, 'hardswish', 1)]
_MBV3_LARGE = [
    (3, 16, 16, False, 'relu', 1), (3, 64, 24, False, 'relu', 2),
    (3, 72, 24, False, 'relu', 1), (5, 72, 40, True, 'relu', 2),
    (5, 120, 40, True, 'relu', 1), (5, 120, 40, True, 'relu', 1),
    (3, 240, 80, False, 'hardswish', 2), (3, 200, 80, False, 'hardswish', 1),
    (3, 184, 80, False, 'hardswish', 1), (3, 184, 80, False, 'hardswish', 1),
    (3, 480, 112, True, 'hardswish', 1), (3, 672, 112, True, 'hardswish', 1),
    (5, 672, 160, True, 'hardswish', 2), (5, 960, 160, True, 'hardswish', 1),
    (5, 960, 160, True, 'hardswish', 1)]


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, num_classes=1000):
        super().__init__()
        layers = [_conv_bn(3, 16, 3, stride=2, padding=1, act='hardswish')]
        in_c = 16
        for k, exp, out_c, se, act, s in cfg:
            layers.append(_MBV3Block(in_c, exp, out_c, k, s, se, act))
            in_c = out_c
        exp_last = cfg[-1][1]
        layers.append(_conv_bn(in_c, exp_last, 1, act='hardswish'))
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.classifier = nn.Sequential(
            nn.Linear(exp_last, last_c), nn.Hardswish(),
            nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        return self.classifier(self.avgpool(self.features(x)).flatten(1))


def mobilenet_v3_small(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNetV3(_MBV3_SMALL, 1024, **kw)


def mobilenet_v3_large(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNetV3(_MBV3_LARGE, 1280, **kw)
