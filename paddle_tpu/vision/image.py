"""paddle.vision.image (upstream: python/paddle/vision/image.py):
image IO with a pluggable backend. Backends: 'pil' (decode via Pillow,
returned as HWC uint8 ndarray — this framework's transform currency)
and 'cv2' when OpenCV is importable."""
from __future__ import annotations

import numpy as np

_BACKEND = 'pil'


def set_image_backend(backend: str):
    global _BACKEND
    if backend not in ('pil', 'cv2'):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    if backend == 'cv2':
        try:
            import cv2  # noqa: F401
        except ImportError as e:
            raise ImportError('cv2 backend requested but OpenCV is not '
                              'installed') from e
    _BACKEND = backend


def get_image_backend() -> str:
    return _BACKEND


def image_load(path, backend=None):
    """Load an image file as HWC uint8 (RGB for color images)."""
    backend = backend or _BACKEND
    if backend == 'cv2':
        import cv2
        # IMREAD_COLOR: always 3-channel 8-bit — same contract as pil
        # (alpha dropped, 16-bit downconverted)
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError(f'cv2 failed to read {path!r}')
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert('RGB') if im.mode not in ('L', 'RGB')
                          else im)
