"""paddle.vision.transforms (upstream: python/paddle/vision/transforms/).

Numpy-based: transforms run in DataLoader workers on the host (where the
C++ decoder pool does the heavy copies); only the final batch hits the
device. Images are HWC uint8/float arrays; ToTensor converts to CHW
float32 in [0, 1].
"""
from __future__ import annotations

import numbers
from typing import List, Sequence, Tuple, Union

import numpy as np

from ..tensor import Tensor


def _is_chw(img):
    """Heuristic for channel-first layout (what ToTensor emits): a
    leading 1/3/4-channel dim with a non-channel-sized trailing dim."""
    return (img.ndim == 3 and img.shape[0] in (1, 3, 4)
            and img.shape[2] not in (1, 3, 4))


def _as_hwc(img):
    img = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW'):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_hwc(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if self.data_format == 'CHW':
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False):
        self.mean = np.asarray(
            [mean] if isinstance(mean, numbers.Number) else mean,
            np.float32)
        self.std = np.asarray(
            [std] if isinstance(std, numbers.Number) else std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == 'CHW':
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    """Nearest/bilinear resize on HWC arrays (pure numpy)."""

    def __init__(self, size, interpolation='bilinear'):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if (h, w) == (th, tw):
            return img
        if self.interpolation == 'nearest':
            ri = (np.arange(th) * h / th).astype(int).clip(0, h - 1)
            ci = (np.arange(tw) * w / tw).astype(int).clip(0, w - 1)
            return img[ri][:, ci]
        # bilinear
        ys = (np.arange(th) + 0.5) * h / th - 0.5
        xs = (np.arange(tw) + 0.5) * w / tw - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0, 1)[:, None, None]
        wx = np.clip(xs - x0, 0, 1)[None, :, None]
        f = img.astype(np.float32)
        out = ((f[y0][:, x0] * (1 - wy) + f[y1][:, x0] * wy) * (1 - wx)
               + (f[y0][:, x1] * (1 - wy) + f[y1][:, x1] * wy) * wx)
        return out.astype(img.dtype) if img.dtype == np.uint8 \
            else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(th - h, 0), max(tw - w, 0)
            img = np.pad(img, ((0, ph), (0, pw), (0, 0)))
            h, w = img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.rand() < self.prob:
            return img[::-1].copy()
        return img


def to_tensor(img, data_format='CHW'):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format='CHW'):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation='bilinear'):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant'):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = tuple(padding)  # left, top, right, bottom
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _as_hwc(img)
        l, t, r, b = self.padding
        spec = ((t, b), (l, r), (0, 0))
        if self.padding_mode == 'constant':
            return np.pad(img, spec, constant_values=self.fill)
        return np.pad(img, spec, mode=self.padding_mode)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue on HWC images (each
    factor sampled like upstream: U[max(0,1-f), 1+f]; hue in [-h, h])."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    @staticmethod
    def _factor(f):
        # upstream accepts float f -> U[max(0,1-f), 1+f], or an explicit
        # (min, max) range
        if isinstance(f, (tuple, list)):
            return np.random.uniform(f[0], f[1])
        return np.random.uniform(max(0.0, 1 - f), 1 + f)

    def _apply_image(self, img):
        img = _as_hwc(img)
        was_u8 = img.dtype == np.uint8
        f = img.astype(np.float32) / (255.0 if was_u8 else 1.0)
        if self.brightness:
            f = f * self._factor(self.brightness)
        if self.contrast:
            mean = f.mean()
            f = (f - mean) * self._factor(self.contrast) + mean
        if self.saturation:
            grey = f.mean(axis=2, keepdims=True)
            f = (f - grey) * self._factor(self.saturation) + grey
        if self.hue and f.shape[2] == 3:
            # cheap hue rotation: roll channels by a blended amount
            h = self.hue if isinstance(self.hue, (tuple, list)) \
                else (-self.hue, self.hue)
            theta = np.random.uniform(h[0], h[1]) * 2 * np.pi
            cos_t, sin_t = np.cos(theta), np.sin(theta)
            one3 = 1.0 / 3.0
            sq3 = np.sqrt(1.0 / 3.0)
            m = (cos_t * np.eye(3)
                 + (1 - cos_t) * np.full((3, 3), one3)
                 + sin_t * sq3 * np.array([[0, -1, 1],
                                           [1, 0, -1],
                                           [-1, 1, 0]], np.float32))
            f = f @ m.T.astype(np.float32)
        f = np.clip(f, 0, 1)
        return (f * 255).astype(np.uint8) if was_u8 else f


class RandomRotation(BaseTransform):
    """Rotate by a random angle in [-degrees, degrees] (bilinear, same
    output size, zero fill) — pure numpy inverse-mapping."""

    def __init__(self, degrees, interpolation='bilinear', fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        h, w = img.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        cos_a, sin_a = np.cos(angle), np.sin(angle)
        # inverse rotation as a 3x3 (x, y, 1) map into _warp_inverse
        inv = np.array(
            [[cos_a, -sin_a, cx - cos_a * cx + sin_a * cy],
             [sin_a, cos_a, cy - sin_a * cx - cos_a * cy],
             [0.0, 0.0, 1.0]], np.float32)
        return _warp_inverse(img, inv, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img)
        grey = img.astype(np.float32).mean(axis=2, keepdims=True)
        if self.num_output_channels == 3:
            grey = np.repeat(grey, 3, axis=2)
        return grey.astype(img.dtype) if img.dtype == np.uint8 else grey


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation='bilinear'):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(*np.log(self.ratio)))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = img[i:i + ch, j:j + cw]
                return Resize(self.size, self.interpolation)(crop)
        return Resize(self.size, self.interpolation)(
            CenterCrop(min(h, w))(img))


def pad(img, padding, fill=0, padding_mode='constant'):
    return Pad(padding, fill, padding_mode)(img)


def rotate(img, angle, interpolation='bilinear', fill=0):
    t = RandomRotation((angle, angle), interpolation, fill)
    return t._apply_image(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def vflip(img):
    return _as_hwc(img)[::-1].copy()


class Transpose(BaseTransform):
    """HWC ndarray -> CHW (upstream paddle.vision.transforms.Transpose;
    default order (2, 0, 1))."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        return np.transpose(_as_hwc(img), self.order)


class BrightnessTransform(BaseTransform):
    """Single-factor brightness jitter (upstream transforms of the same
    name): value in [max(0,1-v), 1+v] like ColorJitter's one channel."""

    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return ColorJitter(brightness=self.value)._apply_image(img)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return ColorJitter(contrast=self.value)._apply_image(img)


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return ColorJitter(saturation=self.value)._apply_image(img)


class HueTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return ColorJitter(hue=self.value)._apply_image(img)


class RandomErasing(BaseTransform):
    """Erase a random rectangle (upstream RandomErasing / arXiv
    1708.04896): area ratio in `scale`, aspect in `ratio`, filled with
    `value` (or per-pixel noise when value='random')."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale, self.ratio = scale, ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = _is_chw(img)  # post-ToTensor layout: erase spatially
        if chw:
            img = np.transpose(img, (1, 2, 0))
        img = _as_hwc(img)
        if np.random.uniform() >= self.prob:
            return np.transpose(img, (2, 0, 1)) if chw else img
        h, w = img.shape[:2]
        area = h * w
        out = img if self.inplace else img.copy()
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(*np.log(self.ratio)))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if isinstance(self.value, str):  # 'random'
                    patch = np.random.uniform(
                        0, 255 if img.dtype == np.uint8 else 1.0,
                        (eh, ew) + img.shape[2:])
                    out[i:i + eh, j:j + ew] = patch.astype(img.dtype)
                else:
                    out[i:i + eh, j:j + ew] = self.value
                break
        return np.transpose(out, (2, 0, 1)) if chw else out


def _warp_inverse(img, inv3x3, fill=0):
    """Bilinear warp by an inverse 3x3 projective map (dest -> src),
    shared by RandomAffine / RandomPerspective."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    ones = np.ones_like(xx)
    src = inv3x3 @ np.stack([xx.ravel(), yy.ravel(), ones.ravel()])
    sx = (src[0] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2]))
    sy = (src[1] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2]))
    sx, sy = sx.reshape(h, w), sy.reshape(h, w)
    # epsilon tolerance: float32 homography math can put border pixels a
    # hair outside [0, size-1] and they must not drop to fill
    tol = 1e-3
    valid = (sy >= -tol) & (sy <= h - 1 + tol) \
        & (sx >= -tol) & (sx <= w - 1 + tol)
    sy = sy.clip(0, h - 1)
    sx = sx.clip(0, w - 1)
    y0 = np.floor(sy).astype(int)
    x0 = np.floor(sx).astype(int)
    wy = (sy - y0)[..., None]
    wx = (sx - x0)[..., None]
    y0c, x0c = y0.clip(0, h - 1), x0.clip(0, w - 1)
    y1c, x1c = (y0 + 1).clip(0, h - 1), (x0 + 1).clip(0, w - 1)
    f = img.astype(np.float32)
    out = ((f[y0c, x0c] * (1 - wy) + f[y1c, x0c] * wy) * (1 - wx)
           + (f[y0c, x1c] * (1 - wy) + f[y1c, x1c] * wy) * wx)
    out = np.where(valid[..., None], out, np.float32(fill))
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


class RandomAffine(BaseTransform):
    """Random rotation + translation + scale + shear (upstream
    RandomAffine), realized as one inverse-mapped bilinear warp."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation='bilinear', fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.translate = translate
        self.scale_range = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        s = np.random.uniform(*self.scale_range) \
            if self.scale_range is not None else 1.0
        shx = shy = 0.0
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, numbers.Number):
                sh = (-sh, sh)
            shx = np.deg2rad(np.random.uniform(sh[0], sh[1]))
            if len(sh) == 4:
                shy = np.deg2rad(np.random.uniform(sh[2], sh[3]))
        cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if self.center is None \
            else (self.center[1], self.center[0])
        cos_a, sin_a = np.cos(angle), np.sin(angle)
        # forward: T(center) @ R @ Shear @ S @ T(-center) + t
        rs = np.array([[cos_a, -sin_a], [sin_a, cos_a]], np.float32) @ \
            np.array([[1, np.tan(shx)], [np.tan(shy), 1]], np.float32) * s
        fwd = np.eye(3, dtype=np.float32)
        fwd[:2, :2] = rs
        fwd[0, 2] = cx + tx - rs[0, 0] * cx - rs[0, 1] * cy
        fwd[1, 2] = cy + ty - rs[1, 0] * cx - rs[1, 1] * cy
        inv = np.linalg.inv(fwd)
        return _warp_inverse(img, inv.astype(np.float32), self.fill)


class RandomPerspective(BaseTransform):
    """Random 4-point perspective warp (upstream RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation='bilinear', fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    @staticmethod
    def _solve_homography(src, dst):
        """3x3 H with H @ src_i ~ dst_i (both [4, 2], x-y order)."""
        a = []
        for (x, y), (u, v) in zip(src, dst):
            a.append([x, y, 1, 0, 0, 0, -u * x, -u * y, -u])
            a.append([0, 0, 0, x, y, 1, -v * x, -v * y, -v])
        _, _, vt = np.linalg.svd(np.asarray(a, np.float64))
        hmat = vt[-1].reshape(3, 3)
        return (hmat / hmat[2, 2]).astype(np.float32)

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.uniform() >= self.prob:
            return img
        h, w = img.shape[:2]
        d = self.distortion_scale
        dx, dy = w * d / 2.0, h * d / 2.0
        corners = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1],
                            [0, h - 1]], np.float32)
        jitter = np.random.uniform(0, 1, (4, 2)).astype(np.float32) * \
            np.array([dx, dy], np.float32)
        signs = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]], np.float32)
        dst = corners + jitter * signs
        # inverse map: dest corners -> source corners
        inv = self._solve_homography(dst, corners)
        return _warp_inverse(img, inv, self.fill)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width].copy()


def erase(img, i, j, h, w, v, inplace=False):
    img = np.asarray(img)
    if _is_chw(img):  # channel-first: erase the spatial rectangle
        out = img if inplace else img.copy()
        out[:, i:i + h, j:j + w] = v
        return out
    img = _as_hwc(img)
    out = img if inplace else img.copy()
    out[i:i + h, j:j + w] = v
    return out


def adjust_brightness(img, brightness_factor):
    return ColorJitter(
        brightness=(brightness_factor, brightness_factor))._apply_image(img)


def adjust_contrast(img, contrast_factor):
    return ColorJitter(
        contrast=(contrast_factor, contrast_factor))._apply_image(img)


def adjust_hue(img, hue_factor):
    return ColorJitter(hue=(hue_factor, hue_factor))._apply_image(img)
