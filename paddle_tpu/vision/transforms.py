"""paddle.vision.transforms (upstream: python/paddle/vision/transforms/).

Numpy-based: transforms run in DataLoader workers on the host (where the
C++ decoder pool does the heavy copies); only the final batch hits the
device. Images are HWC uint8/float arrays; ToTensor converts to CHW
float32 in [0, 1].
"""
from __future__ import annotations

import numbers
from typing import List, Sequence, Tuple, Union

import numpy as np

from ..tensor import Tensor


def _as_hwc(img):
    img = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW'):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_hwc(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if self.data_format == 'CHW':
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False):
        self.mean = np.asarray(
            [mean] if isinstance(mean, numbers.Number) else mean,
            np.float32)
        self.std = np.asarray(
            [std] if isinstance(std, numbers.Number) else std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == 'CHW':
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    """Nearest/bilinear resize on HWC arrays (pure numpy)."""

    def __init__(self, size, interpolation='bilinear'):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if (h, w) == (th, tw):
            return img
        if self.interpolation == 'nearest':
            ri = (np.arange(th) * h / th).astype(int).clip(0, h - 1)
            ci = (np.arange(tw) * w / tw).astype(int).clip(0, w - 1)
            return img[ri][:, ci]
        # bilinear
        ys = (np.arange(th) + 0.5) * h / th - 0.5
        xs = (np.arange(tw) + 0.5) * w / tw - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0, 1)[:, None, None]
        wx = np.clip(xs - x0, 0, 1)[None, :, None]
        f = img.astype(np.float32)
        out = ((f[y0][:, x0] * (1 - wy) + f[y1][:, x0] * wy) * (1 - wx)
               + (f[y0][:, x1] * (1 - wy) + f[y1][:, x1] * wy) * wx)
        return out.astype(img.dtype) if img.dtype == np.uint8 \
            else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(th - h, 0), max(tw - w, 0)
            img = np.pad(img, ((0, ph), (0, pw), (0, 0)))
            h, w = img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        img = _as_hwc(img)
        if np.random.rand() < self.prob:
            return img[::-1].copy()
        return img


def to_tensor(img, data_format='CHW'):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format='CHW'):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation='bilinear'):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
