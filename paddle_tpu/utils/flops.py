"""paddle.flops (upstream: python/paddle/hapi/dynamic_flops.py) —
per-layer FLOP counting via forward-post hooks over a dry-run forward.

Counts multiply-accumulate-style FLOPs (2 * MACs) for the compute
layers and elementwise costs for norms/activations — same conventions
as the upstream counter."""
from __future__ import annotations

from typing import Optional

import numpy as np

from .. import nn


def _out_shape(out):
    if isinstance(out, (tuple, list)):
        out = out[0]
    return tuple(int(s) for s in out.shape)


def _count(layer, inputs, out) -> Optional[int]:
    from ..nn.common_layers import Embedding, Linear
    from ..nn.conv import _ConvNd
    in_shape = tuple(int(s) for s in inputs[0].shape) if inputs else ()
    o = _out_shape(out)
    if isinstance(layer, Linear):
        rows = int(np.prod(o[:-1])) if len(o) > 1 else 1
        return 2 * rows * layer.in_features * layer.out_features
    if isinstance(layer, _ConvNd):
        k = int(np.prod(layer.kernel_size))
        in_c = layer.in_channels // layer.groups
        return 2 * int(np.prod(o)) * in_c * k
    if isinstance(layer, Embedding):
        return 0  # gather, no FLOPs by upstream convention
    name = type(layer).__name__
    if 'Norm' in name:
        return 2 * int(np.prod(in_shape))
    if 'Pool' in name or name in ('ReLU', 'GELU', 'Sigmoid', 'Tanh',
                                  'Hardswish', 'Hardsigmoid', 'Swish',
                                  'ReLU6', 'LeakyReLU', 'Softmax'):
        return int(np.prod(o))
    return None  # containers and unknown layers: children count instead


def summary(net: nn.Layer, input_size=None, dtypes=None, input=None):
    """paddle.summary (upstream python/paddle/hapi/model_summary.py):
    dry-run + per-layer output-shape/param table; returns the totals
    dict like upstream."""
    import paddle_tpu as paddle

    rows = []
    hooks = []

    def make_hook(path):
        def hook(layer, inputs, out):
            if layer._sub_layers:
                return  # leaf layers only, like upstream's table
            o = out[0] if isinstance(out, (tuple, list)) else out
            n_params = int(sum(np.prod(p.shape)
                               for p in layer.parameters(
                                   include_sublayers=False)))
            rows.append((path or type(layer).__name__,
                         type(layer).__name__,
                         list(getattr(o, 'shape', [])), n_params))
        return hook

    for path, sub in net.named_sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(make_hook(path)))
    was_training = net.training
    net.eval()
    try:
        x = input if input is not None else paddle.zeros(list(input_size))
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(np.prod(p.shape) for p in net.parameters()
                        if not p.stop_gradient))
    header = f'{"Layer (type)":<40}{"Output Shape":<24}{"Param #":>12}'
    lines = [header, '-' * len(header)]
    for path, tname, shape, n in rows:
        lines.append(f'{path + " (" + tname + ")":<40}'
                     f'{str(shape):<24}{n:>12,}')
    lines += ['-' * len(header),
              f'Total params: {total:,}',
              f'Trainable params: {trainable:,}',
              f'Non-trainable params: {total - trainable:,}']
    print('\n'.join(lines))
    return {'total_params': total, 'trainable_params': trainable}


def flops(net: nn.Layer, input_size, custom_ops=None,
          print_detail: bool = False) -> int:
    """Dry-run `net` on zeros of `input_size` and return total FLOPs.

    `custom_ops` maps layer CLASS -> fn(layer, inputs, output) -> flops,
    overriding the built-in table (upstream-compatible signature).
    """
    import paddle_tpu as paddle

    totals = {}
    hooks = []

    def make_hook(path):
        def hook(layer, inputs, out):
            fn = (custom_ops or {}).get(type(layer))
            n = fn(layer, inputs, out) if fn \
                else _count(layer, inputs, out)
            if n:
                totals[path] = totals.get(path, 0) + int(n)
        return hook

    for path, sub in net.named_sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(make_hook(path or
                                                              'net')))
    was_training = net.training
    net.eval()
    try:
        x = paddle.zeros(list(input_size))
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    total = sum(totals.values())
    if print_detail:
        for path, n in sorted(totals.items()):
            print(f'{path:50s} {n:,}')
    print(f'Total Flops: {total:,}     Total Params: '
          f'{int(sum(np.prod(p.shape) for p in net.parameters())):,}')
    return total
