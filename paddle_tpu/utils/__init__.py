"""paddle_tpu.utils — checkpointing, logging, misc support."""
from . import checkpoint  # noqa: F401
from . import logging  # noqa: F401
from . import unique_name  # noqa: F401


class _DLPack:
    """paddle.utils.dlpack (upstream: python/paddle/utils/dlpack.py) —
    zero-copy exchange via the DLPack protocol on jax arrays."""

    @staticmethod
    def to_dlpack(x):
        """Returns a DLPack-protocol object (the raw jax array — it
        implements __dlpack__/__dlpack_device__; capsule-style dlpack
        was removed from modern jax/numpy/torch)."""
        from ..tensor import Tensor
        return x.value if isinstance(x, Tensor) else x

    @staticmethod
    def from_dlpack(ext):
        from ..tensor import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.from_dlpack(ext))


dlpack = _DLPack()


def try_import(module_name, err_msg=None):
    """Import a module or raise with an install hint (upstream
    paddle.utils.try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f'{module_name} is required for this API; '
                       f'install it first') from e


def deprecated(update_to='', since='', reason='', level=0):
    """Decorator stamping a DeprecationWarning on calls (upstream
    paddle.utils.deprecated)."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f'API {fn.__name__} is deprecated'
            if since:
                msg += f' since {since}'
            if update_to:
                msg += f'; use {update_to} instead'
            if reason:
                msg += f' ({reason})'
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def run_check():
    """Sanity-check the install: device visible, one matmul + grad on
    the real backend, and a psum collective across all local devices
    (upstream paddle.utils.run_check)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    dev = jax.devices()[0]
    kind = getattr(dev, 'device_kind', jax.default_backend())
    x = paddle.randn([4, 4])
    x.stop_gradient = False
    y = (paddle.matmul(x, x) ** 2).sum()
    y.backward()
    assert x.grad is not None
    n = jax.device_count()
    # real collective over every LOCAL device (pmap cannot span hosts)
    nl = jax.local_device_count()
    psum = jax.pmap(lambda v: jax.lax.psum(v, 'i'), axis_name='i')(
        jnp.ones((nl,)))
    assert np.allclose(np.asarray(psum), nl)
    print(f'paddle_tpu is installed successfully! '
          f'backend={jax.default_backend()} device_kind={kind} '
          f'device_count={n} (matmul+grad OK, psum over {n} device(s) OK)')
