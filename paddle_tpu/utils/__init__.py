"""paddle_tpu.utils — checkpointing, logging, misc support."""
from . import checkpoint  # noqa: F401
from . import logging  # noqa: F401
