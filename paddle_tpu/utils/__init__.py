"""paddle_tpu.utils — checkpointing, logging, misc support."""
from . import checkpoint  # noqa: F401
from . import logging  # noqa: F401
from . import unique_name  # noqa: F401


class _DLPack:
    """paddle.utils.dlpack (upstream: python/paddle/utils/dlpack.py) —
    zero-copy exchange via the DLPack protocol on jax arrays."""

    @staticmethod
    def to_dlpack(x):
        """Returns a DLPack-protocol object (the raw jax array — it
        implements __dlpack__/__dlpack_device__; capsule-style dlpack
        was removed from modern jax/numpy/torch)."""
        from ..tensor import Tensor
        return x.value if isinstance(x, Tensor) else x

    @staticmethod
    def from_dlpack(ext):
        from ..tensor import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.from_dlpack(ext))


dlpack = _DLPack()
