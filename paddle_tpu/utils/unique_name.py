"""paddle.utils.unique_name (upstream: python/paddle/utils/unique_name.py):
process-wide unique name generation for layers/ops, with guard() scoping
so name sequences are reproducible across program builds."""
from __future__ import annotations

import contextlib
from typing import Dict, Optional


class UniqueNameGenerator:
    def __init__(self, prefix: str = ''):
        self.prefix = prefix
        self._ids: Dict[str, int] = {}

    def __call__(self, key: str) -> str:
        i = self._ids.get(key, 0)
        self._ids[key] = i + 1
        return f'{self.prefix}{key}_{i}'


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    """Next unique name for `key`: 'fc_0', 'fc_1', ..."""
    return _generator(key)


def switch(new_generator: Optional[UniqueNameGenerator] = None):
    """Swap the active generator; returns the previous one."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None \
        else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope with a fresh (or given) name sequence; restores the previous
    generator on exit. A str/bytes argument becomes the prefix."""
    if isinstance(new_generator, (str, bytes)):
        new_generator = UniqueNameGenerator(
            new_generator.decode() if isinstance(new_generator, bytes)
            else new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
