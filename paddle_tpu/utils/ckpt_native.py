"""ctypes bindings for the C++ checkpoint sharder (csrc/ckpt_sharder.cpp).

Same build/degrade contract as io/native.py: compiled on first use with
g++, cached under csrc/build/, rebuilt when the source is newer, and
`available()` returns False (callers fall back to the single-stream npz
container) when no compiler is present.

A sharded checkpoint directory holds `manifest.json` plus
`shard_<k>.bin` files; arrays are packed back-to-back per shard, and
shards are written/read by one C++ thread each.
"""
from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Dict, List, Tuple

import numpy as np

from ..analysis.runtime import concurrency as _concurrency

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), 'csrc')
_BUILD = os.path.join(_CSRC, 'build')
_LIB_PATH = os.path.join(_BUILD, 'libpaddle_tpu_ckpt.so')
_SRC = os.path.join(_CSRC, 'ckpt_sharder.cpp')

_lock = _concurrency.Lock('ckpt_native._lock')
_lib = None
_tried = False

MANIFEST = 'manifest.json'


def _build():
    os.makedirs(_BUILD, exist_ok=True)
    tmp = _LIB_PATH + '.tmp.so'
    subprocess.run(
        ['g++', '-O3', '-fPIC', '-shared', '-std=c++17', '-pthread',
         _SRC, '-o', tmp],
        check=True, capture_output=True)
    os.replace(tmp, _LIB_PATH)


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def _bind(lib):
    pp = ctypes.POINTER(ctypes.c_char_p)
    for name in ('ckpt_write', 'ckpt_read'):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [pp, ctypes.c_int,
                       ctypes.POINTER(ctypes.c_longlong),
                       ctypes.POINTER(ctypes.c_void_p),
                       ctypes.POINTER(ctypes.c_ulonglong)]
    return lib


def get_lib():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _stale():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception:  # paddle-lint: disable=swallowed-exception -- optional native lib gate; absence is a supported config surfaced via available()
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


def _plan_shards(sizes: List[int], n_shards: int) -> List[List[int]]:
    """Greedy size-balanced assignment: largest array to lightest shard.
    Returns per-shard lists of array indices."""
    n_shards = max(1, min(n_shards, max(len(sizes), 1)))
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    loads = [0] * n_shards
    shards: List[List[int]] = [[] for _ in range(n_shards)]
    for i in order:
        k = loads.index(min(loads))
        shards[k].append(i)
        loads[k] += sizes[i]
    return [s for s in shards if s]


def _call(lib_fn, dirname: str, per_shard_arrays: List[List[np.ndarray]]):
    """Marshal per-shard array lists into the flat C arguments and call
    ckpt_write/ckpt_read (arrays of shard k go to shard_<k>.bin, packed
    back-to-back in list order)."""
    paths, starts, ptrs, sizes = [], [0], [], []
    for k, arrs in enumerate(per_shard_arrays):
        paths.append(
            os.path.join(dirname, f'shard_{k}.bin').encode('utf-8'))
        for a in arrs:
            ptrs.append(a.ctypes.data)
            sizes.append(a.nbytes)
        starts.append(starts[-1] + len(arrs))
    rc = lib_fn(
        (ctypes.c_char_p * len(paths))(*paths), len(paths),
        (ctypes.c_longlong * len(starts))(*starts),
        (ctypes.c_void_p * max(len(ptrs), 1))(*ptrs),
        (ctypes.c_ulonglong * max(len(sizes), 1))(*sizes))
    if rc:
        raise IOError(f'checkpoint shard io failed on '
                      f'{os.path.join(dirname, f"shard_{rc - 1}.bin")}')


def write_shards(dirname: str, named: Dict[str, np.ndarray],
                 n_shards: int = 8) -> None:
    """Write `named` arrays as a sharded checkpoint directory."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError('native checkpoint sharder unavailable')
    os.makedirs(dirname, exist_ok=True)
    names = list(named)
    arrays = [np.ascontiguousarray(named[n]) for n in names]
    shards = _plan_shards([a.nbytes for a in arrays], n_shards)
    entries = {}
    for k, idxs in enumerate(shards):
        off = 0
        for i in idxs:
            a = arrays[i]
            entries[names[i]] = {
                'shard': k, 'offset': off, 'nbytes': a.nbytes,
                'dtype': a.dtype.str, 'shape': list(a.shape)}
            off += a.nbytes
    _call(lib.ckpt_write, dirname,
          [[arrays[i] for i in idxs] for idxs in shards])
    with open(os.path.join(dirname, MANIFEST), 'w') as f:
        json.dump({'n_shards': len(shards), 'arrays': entries}, f)


def read_shards(dirname: str) -> Dict[str, np.ndarray]:
    """Read a sharded checkpoint directory back into named arrays."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError('native checkpoint sharder unavailable')
    with open(os.path.join(dirname, MANIFEST)) as f:
        manifest = json.load(f)
    entries = manifest['arrays']
    out = {name: np.empty(e['shape'], dtype=np.dtype(e['dtype']))
           for name, e in entries.items()}
    per_shard: List[List[Tuple[int, str]]] = [
        [] for _ in range(manifest['n_shards'])]
    for name, e in entries.items():
        per_shard[e['shard']].append((e['offset'], name))
    _call(lib.ckpt_read, dirname,
          [[out[name] for _, name in sorted(members)]
           for members in per_shard])
    return out
