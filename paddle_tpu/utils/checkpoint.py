"""Step-indexed training checkpoints (upstream: fleet checkpointing +
paddle.distributed.fleet.utils / hapi Checkpoint callback).

TPU-native design: one CheckpointManager with two interchangeable
backends — orbax (sharded jax arrays, multi-host aware, async) when
available, and the npz serialization container as fallback. A checkpoint
is a pytree: {'params', 'opt_state', 'rng_key', 'step', 'meta', ...};
restore is bit-exact (tested: resumed run reproduces the uninterrupted
loss trajectory).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .. import observability as _obs
from .. import serialization
from ..resilience.retry import RetryPolicy, call_with_retry

_STEP_RE = re.compile(r'^step_(\d+)$')


def _tree_bytes(tree: Any) -> int:
    """Payload size of a host pytree (array leaves only)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, 'nbytes', None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def _payload_checksums(d: str) -> Dict[str, str]:
    """sha256 of every payload file under a checkpoint dir (the
    _COMMITTED manifest itself excluded), keyed by relative path."""
    out: Dict[str, str] = {}
    for root, _, files in os.walk(d):
        for name in sorted(files):
            if name == '_COMMITTED':
                continue
            path = os.path.join(root, name)
            h = hashlib.sha256()
            with open(path, 'rb') as f:
                for chunk in iter(lambda: f.read(1 << 20), b''):
                    h.update(chunk)
            out[os.path.relpath(path, d)] = h.hexdigest()
    return out


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception:  # paddle-lint: disable=swallowed-exception -- orbax is an optional backend; None routes to the native npz path
        return None


class CheckpointManager:
    """Save/restore step-indexed checkpoints with retention.

    Args mirror orbax's manager (max_to_keep, save_interval_steps); the
    backend is chosen automatically ('orbax' | 'npz').
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1, async_save: bool = False,
                 backend: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        self.async_save = async_save
        self._ocp = _try_orbax() if backend in (None, 'orbax') else None
        if backend == 'orbax' and self._ocp is None:
            raise RuntimeError('orbax backend requested but not importable')
        if backend == 'native':
            from . import ckpt_native
            if not ckpt_native.available():
                raise RuntimeError(
                    'native backend requested but the C++ checkpoint '
                    'sharder is unavailable (no compiler?)')
            self.backend = 'native'
        else:
            self.backend = 'orbax' if self._ocp is not None else 'npz'
        self._pending: Optional[threading.Thread] = None
        # transient I/O failures (flaky NFS/GCS mounts) are retried with
        # backoff before a save/restore is declared dead
        self._retry_policy = retry_policy or RetryPolicy()
        self._writer_exc: Optional[BaseException] = None

    # -- bookkeeping --------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f'step_{step}')

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(
                    os.path.join(self.directory, name, '_COMMITTED')):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def should_save(self, step: int) -> bool:
        return step % self.save_interval_steps == 0

    # -- save/restore -------------------------------------------------------
    @staticmethod
    def _to_host(tree: Any):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x.value) if hasattr(x, 'value')
            else (np.asarray(x) if hasattr(x, 'shape') or isinstance(
                x, (int, float)) else x), tree)

    def _write_once(self, step: int, host_tree: Any, cursor=None):
        """One write attempt: tmp dir → serialize → commit marker →
        atomic rename. Re-entrant (the tmp dir is recreated), so the
        retry wrapper can safely re-run it after a transient failure."""
        d = self._step_dir(step)
        tmp = d + '.tmp'
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        if self.backend == 'orbax':
            ckptr = self._ocp.StandardCheckpointer()
            ckptr.save(os.path.join(tmp, 'tree'), host_tree)
            ckptr.wait_until_finished()
        elif self.backend == 'native':
            serialization.save_sharded(host_tree,
                                       os.path.join(tmp, 'tree_sharded'))
        else:
            serialization.save(host_tree, os.path.join(tmp, 'tree.npz'))
        committed = {'step': step, 'backend': self.backend,
                     'checksums': _payload_checksums(tmp)}
        if cursor is not None:
            committed['dataloader'] = cursor
        with open(os.path.join(tmp, '_COMMITTED'), 'w') as f:
            json.dump(committed, f)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)
        self._gc()

    def _write(self, step: int, host_tree: Any, cursor=None):
        nbytes = _tree_bytes(host_tree)
        with _obs.span('checkpoint_save', step=step, bytes=nbytes):
            call_with_retry(self._write_once, step, host_tree, cursor,
                            policy=self._retry_policy,
                            site='checkpoint_save')
        if _obs.enabled():
            reg = _obs.get_registry()
            reg.counter('paddle_checkpoint_saves_total',
                        'committed checkpoint saves').inc()
            reg.counter('paddle_checkpoint_save_bytes_total',
                        'checkpoint payload bytes written').inc(nbytes)

    def save(self, step: int, tree: Any, force: bool = False,
             dataloader: Any = None):
        """Snapshot `tree` at `step`. Respects save_interval unless forced.

        Pass `dataloader=` to record its mid-epoch cursor
        ({epoch, batch_idx}, see DataLoader.state_dict) in the
        _COMMITTED sidecar — outside the tree, so orbax template
        restores are unaffected — letting resume replay the exact
        remaining batch sequence (SURVEY §5 "dataloader epoch/seed
        state")."""
        if not force and not self.should_save(step):
            return False
        cursor = dataloader.state_dict() if dataloader is not None else None
        self.wait_until_finished()
        # snapshot to host SYNCHRONOUSLY: the train loop mutates live
        # Tensors in place, so deferring materialization to the writer
        # thread would tear the checkpoint across steps
        host_tree = self._to_host(tree)
        if self.async_save:
            # the writer thread must not swallow failures: capture the
            # exception and re-raise it from wait_until_finished() / the
            # next save() — a silently-lost checkpoint surfaces only at
            # restore time, which is exactly when it's too late
            def _write_capturing():
                try:
                    self._write(step, host_tree, cursor)
                except BaseException as e:
                    self._writer_exc = e
            self._pending = threading.Thread(
                target=_write_capturing, daemon=True)
            self._pending.start()
        else:
            self._write(step, host_tree, cursor)
        return True

    def verify(self, step: int) -> bool:
        """Recompute the payload checksums of a committed step against
        its _COMMITTED manifest. Manifests from before checksumming
        (no 'checksums' key) pass vacuously."""
        d = self._step_dir(step)
        try:
            with open(os.path.join(d, '_COMMITTED')) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        want = meta.get('checksums')
        if want is None:
            return True
        return _payload_checksums(d) == want

    def restore(self, step: Optional[int] = None,
                template: Any = None, dataloader: Any = None) -> Any:
        """Load a checkpoint tree; with `dataloader=`, also push the
        cursor saved in the _COMMITTED sidecar back into it
        (DataLoader.set_state_dict). A checkpoint whose payload fails
        its manifest checksum (torn write, bit rot) is skipped with a
        `checkpoint_corrupt` event and the previous committed step is
        restored instead — the cursor comes from the step actually
        restored."""
        actual, tree = self._restore_tree(step, template)
        if dataloader is not None:
            with open(os.path.join(self._step_dir(actual),
                                   '_COMMITTED')) as f:
                meta = json.load(f)
            if 'dataloader' in meta:
                dataloader.set_state_dict(meta['dataloader'])
        return tree

    def _restore_tree(self, step: Optional[int] = None,
                      template: Any = None):
        self.wait_until_finished()
        steps = self.all_steps()
        if step is not None:
            steps = [s for s in steps if s <= step]
            if step not in steps:
                raise FileNotFoundError(
                    f'no committed checkpoint for step {step} under '
                    f'{self.directory}')
        if not steps:
            raise FileNotFoundError(
                f'no committed checkpoints under {self.directory}')
        for candidate in reversed(steps):
            if not self.verify(candidate):
                # half-written/corrupt payload: never restore it — fall
                # back to the previous committed step
                _obs.emit('checkpoint_corrupt', step=candidate,
                          directory=self._step_dir(candidate))
                if _obs.enabled():
                    _obs.get_registry().counter(
                        'paddle_checkpoint_corrupt_total',
                        'checkpoints skipped on checksum mismatch').inc()
                continue
            with _obs.span('checkpoint_restore', step=candidate):
                tree = call_with_retry(self._read_tree, candidate,
                                       template,
                                       policy=self._retry_policy,
                                       site='checkpoint_restore')
            if _obs.enabled():
                reg = _obs.get_registry()
                reg.counter('paddle_checkpoint_restores_total',
                            'checkpoint restores').inc()
                reg.counter('paddle_checkpoint_restore_bytes_total',
                            'checkpoint payload bytes read').inc(
                                _tree_bytes(tree))
            return candidate, tree
        raise RuntimeError(
            f'every committed checkpoint under {self.directory} failed '
            f'its checksum')

    def _read_tree(self, step: int, template: Any = None) -> Any:
        d = self._step_dir(step)
        with open(os.path.join(d, '_COMMITTED')) as f:
            meta = json.load(f)
        if meta['backend'] == 'orbax':
            if self._ocp is None:
                raise RuntimeError('checkpoint written by orbax but orbax '
                                   'is unavailable')
            ckptr = self._ocp.StandardCheckpointer()
            if template is not None:
                host_template = jax.tree_util.tree_map(
                    lambda x: np.asarray(x.value) if hasattr(x, 'value')
                    else x, template)
                return ckptr.restore(os.path.join(d, 'tree'),
                                     target=host_template)
            return ckptr.restore(os.path.join(d, 'tree'))
        if meta['backend'] == 'native':
            return serialization.load_sharded(
                os.path.join(d, 'tree_sharded'), return_numpy=True)
        return serialization.load(os.path.join(d, 'tree.npz'),
                                  return_numpy=True)

    def wait_until_finished(self):
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._writer_exc is not None:
            exc, self._writer_exc = self._writer_exc, None
            raise RuntimeError(
                'async checkpoint write failed (checkpoint NOT '
                'committed)') from exc

    def _gc(self):
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)
