"""Metric logging (upstream analogue: VisualDL's LogWriter — here a
JSONL metric log plus a VisualDL-compatible surface).

`SummaryWriter.add_scalar(tag, value, step)` appends one JSON line per
record; `read_jsonl` loads a log back for tooling/tests. Deliberately
plain-file so multi-host pods can write per-host logs with no daemon.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional


class SummaryWriter:
    def __init__(self, logdir: str, filename_suffix: str = ''):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(
            logdir, f'metrics{filename_suffix}.jsonl')
        self._fh = open(self._path, 'a', buffering=1)

    def add_scalar(self, tag: str, value, step: Optional[int] = None,
                   walltime: Optional[float] = None):
        rec = {'tag': tag, 'value': float(value), 'step': step,
               'time': walltime if walltime is not None else time.time()}
        self._fh.write(json.dumps(rec) + '\n')

    def add_scalars(self, main_tag: str, tag_value_dict: Dict[str, Any],
                    step: Optional[int] = None):
        for k, v in tag_value_dict.items():
            self.add_scalar(f'{main_tag}/{k}', v, step)

    def add_text(self, tag: str, text: str, step: Optional[int] = None):
        rec = {'tag': tag, 'text': str(text), 'step': step,
               'time': time.time()}
        self._fh.write(json.dumps(rec) + '\n')

    def add_hparams(self, hparams: Dict[str, Any],
                    metrics: Optional[Dict[str, Any]] = None):
        self.add_text('hparams', json.dumps(
            {'hparams': hparams, 'metrics': metrics or {}}))

    def flush(self):
        self._fh.flush()

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


LogWriter = SummaryWriter  # VisualDL parity alias


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def scalars(path_or_dir: str, tag: str) -> Iterator[Dict[str, Any]]:
    path = path_or_dir
    if os.path.isdir(path):
        path = os.path.join(path, 'metrics.jsonl')
    for rec in read_jsonl(path):
        if rec.get('tag') == tag:
            yield rec
