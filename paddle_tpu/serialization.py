"""paddle.save / paddle.load (upstream: python/paddle/framework/io.py).

TPU-native container: instead of the reference's pickle `.pdparams`, the
object tree is flattened to arrays in one `.npz` plus a JSON manifest of
the structure — portable, mmap-friendly, and loadable with zero
arbitrary-code execution. Supports nested dict/list/tuple of Tensor,
ndarray, scalars, strings, None (e.g. layer state_dicts and optimizer
state_dicts).
"""
from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_ARRAY_KEY = '__arr__'


def _tag_key(k):
    if isinstance(k, bool):   # before int: bool is an int subclass
        return f'b:{k}'
    if isinstance(k, int):
        return f'i:{k}'
    if isinstance(k, float):
        return f'f:{k!r}'
    return f's:{k}'


def _untag_key(tagged: str):
    tag, _, v = tagged.partition(':')
    if tag == 'i':
        return int(v)
    if tag == 'b':
        return v == 'True'
    if tag == 'f':
        return float(v)
    return v


def _encode_array(a: np.ndarray):
    """npz can't store ml_dtypes (bfloat16/fp8 have numpy kind 'V'); view
    them as the same-width uint and record the true dtype name."""
    if a.dtype.kind == 'V':
        name = a.dtype.name
        return a.view(np.dtype(f'u{a.dtype.itemsize}')), name
    return a, None


def _decode_array(a: np.ndarray, np_dtype):
    if np_dtype:
        return a.view(np.dtype(jnp.dtype(np_dtype)))
    return a


def _flatten(obj: Any, arrays: list, path: str):
    if isinstance(obj, Tensor):
        arr, np_dtype = _encode_array(np.asarray(obj.value))
        arrays.append(arr)
        return {_ARRAY_KEY: len(arrays) - 1, 'kind': 'tensor',
                'np_dtype': np_dtype}
    if isinstance(obj, (np.ndarray, jnp.ndarray)):
        arr, np_dtype = _encode_array(np.asarray(obj))
        arrays.append(arr)
        return {_ARRAY_KEY: len(arrays) - 1, 'kind': 'ndarray',
                'np_dtype': np_dtype}
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, (str, int, bool, float)):
                raise TypeError(
                    f'paddle.save dict keys must be str/int/bool/float, '
                    f'got {type(k).__name__} at {path!r}')
        # keys keep their python type ('s:'/'i:'/'b:'/'f:' tagged)
        return {'kind': 'dict',
                'items': [[_tag_key(k), _flatten(v, arrays, f'{path}.{k}')]
                          for k, v in obj.items()]}
    if isinstance(obj, (list, tuple)):
        return {'kind': 'list' if isinstance(obj, list) else 'tuple',
                'items': [_flatten(v, arrays, f'{path}[{i}]')
                          for i, v in enumerate(obj)]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {'kind': 'scalar', 'value': obj}
    if isinstance(obj, (np.integer, np.floating, np.bool_)):
        return {'kind': 'scalar', 'value': obj.item()}
    raise TypeError(
        f'paddle.save cannot serialize {type(obj).__name__} at {path!r}')


def _unflatten(spec, arrays, return_numpy):
    kind = spec['kind']
    if kind in ('tensor', 'ndarray'):
        arr = _decode_array(arrays[f'a{spec[_ARRAY_KEY]}'],
                            spec.get('np_dtype'))
        if kind == 'tensor' and not return_numpy:
            return Tensor(jnp.asarray(arr))
        return arr
    if kind == 'dict':
        return {_untag_key(k): _unflatten(v, arrays, return_numpy)
                for k, v in spec['items']}
    if kind == 'list':
        return [_unflatten(v, arrays, return_numpy) for v in spec['items']]
    if kind == 'tuple':
        return tuple(_unflatten(v, arrays, return_numpy)
                     for v in spec['items'])
    return spec['value']


def save(obj: Any, path: str, protocol=None, **config):
    """Serialize a nested object tree to `path` (npz + manifest)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays: list = []
    manifest = _flatten(obj, arrays, '<root>')
    tmp = path + '.tmp'
    np.savez(tmp, manifest=json.dumps(manifest),
             **{f'a{i}': a for i, a in enumerate(arrays)})
    os.replace(tmp + '.npz' if os.path.exists(tmp + '.npz') else tmp, path)


def load(path: str, return_numpy=False, **config) -> Any:
    """Restore an object tree saved by paddle.save."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    data = np.load(path, allow_pickle=False)
    manifest = json.loads(str(data['manifest']))
    return _unflatten(manifest, data, return_numpy)


def save_sharded(obj: Any, dirname: str, n_shards: int = 8):
    """Serialize like `save`, but through the parallel C++ shard writer
    (csrc/ckpt_sharder.cpp): arrays are size-balanced across
    `shard_<k>.bin` files written by one thread each — no zip/CRC pass,
    so large checkpoints write several times faster than the npz
    container. Layout: tree.json (structure) + manifest.json + shards."""
    from .utils import ckpt_native
    os.makedirs(dirname, exist_ok=True)
    arrays: list = []
    manifest = _flatten(obj, arrays, '<root>')
    ckpt_native.write_shards(
        dirname, {f'a{i}': a for i, a in enumerate(arrays)},
        n_shards=n_shards)
    tmp = os.path.join(dirname, 'tree.json.tmp')
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(dirname, 'tree.json'))


def load_sharded(dirname: str, return_numpy=False) -> Any:
    """Restore an object tree saved by `save_sharded` (parallel C++
    shard reader)."""
    tree_file = os.path.join(dirname, 'tree.json')
    if not os.path.isfile(tree_file):
        raise FileNotFoundError(
            f'{dirname!r} is not a sharded checkpoint (no tree.json)')
    from .utils import ckpt_native
    with open(tree_file) as f:
        manifest = json.load(f)
    return _unflatten(manifest, ckpt_native.read_shards(dirname),
                      return_numpy)
