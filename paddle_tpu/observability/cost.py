"""Per-program XLA cost attribution: the ProgramCatalog.

Every compiled executable this framework creates — the jitted train
step, to_static programs, the serving engine's decode block and
per-bucket prefills, the eager dispatch cache's per-op entries — already
carries free introspection data XLA computes at compile time
(`compiled.cost_analysis()` FLOPs / bytes accessed,
`compiled.memory_analysis()` peak HBM) that we previously threw away.
The catalog records it per *named program* together with compile time,
cumulative invocation count, and host wall time, so
`top_programs()` answers "which programs is this step/decode round
actually spending its time and FLOPs in" — train step vs. decode block
vs. prefill buckets — without a profiler attached.

Zero extra compiles by construction: `wrap_jit` compiles a jitted
callable ONCE through the AOT path (`lower().compile()`) per input
signature and then invokes the captured `Compiled` object directly, so
the cost/memory analyses are read off the very executable that serves
the traffic — the catalog never compiles anything the program would not
have compiled anyway (guarded by the serving zero-recompile tests over
`paddle_jit_compiles_total`). Since the program-store consolidation
(`paddle_tpu.programs`), compilation itself is owned by the store —
`wrap_jit` delegates there, THIS catalog remains the bookkeeping, and
every program is tracked exactly once (tier-1 catalog==store guard).

Hot paths never pay: the eager dispatch cache reports only from its
cold miss path (`note_dispatch_compile`) and its per-op invocation
counts are mirrored at scrape time by a registry collector, exactly
like the `paddle_dispatch_*` metrics.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import metrics as _metrics


class ProgramRecord:
    """One named compiled program's cumulative accounting."""

    __slots__ = ('name', 'kind', 'compile_count', 'compile_seconds',
                 'invocations', 'host_seconds', 'flops', 'bytes_accessed',
                 'peak_memory_bytes', 'argument_bytes', 'output_bytes',
                 'temp_bytes', 'analyzed', 'note')

    def __init__(self, name: str, kind: str = 'jit'):
        self.name = name
        self.kind = kind
        self.compile_count = 0
        self.compile_seconds = 0.0
        self.invocations = 0
        self.host_seconds = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.peak_memory_bytes = 0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.analyzed = False
        self.note = ''

    def as_dict(self) -> Dict[str, Any]:
        return {
            'name': self.name, 'kind': self.kind,
            'compile_count': self.compile_count,
            'compile_seconds': self.compile_seconds,
            'invocations': self.invocations,
            'host_seconds': self.host_seconds,
            'flops': self.flops, 'bytes_accessed': self.bytes_accessed,
            'peak_memory_bytes': self.peak_memory_bytes,
            'argument_bytes': self.argument_bytes,
            'output_bytes': self.output_bytes,
            'temp_bytes': self.temp_bytes,
            'analyzed': self.analyzed, 'note': self.note,
        }


def _read_analysis(compiled, record: ProgramRecord):
    """Fill a record from a jax `Compiled` object's free introspection.
    Cumulative across signatures: a program recompiled at a second
    shape (to_static buckets) keeps the LARGEST figures — the report
    attributes the expensive variant."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            record.flops = max(record.flops, float(ca.get('flops', 0.0)))
            record.bytes_accessed = max(
                record.bytes_accessed, float(ca.get('bytes accessed', 0.0)))
            record.analyzed = True
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, 'argument_size_in_bytes', 0) or 0)
            out = int(getattr(ma, 'output_size_in_bytes', 0) or 0)
            tmp = int(getattr(ma, 'temp_size_in_bytes', 0) or 0)
            alias = int(getattr(ma, 'alias_size_in_bytes', 0) or 0)
            peak = int(getattr(ma, 'peak_memory_in_bytes', 0) or 0)
            if not peak:
                # CPU/older backends report no live peak: the resident
                # footprint bound is args + temps + outputs - aliased
                peak = max(arg + tmp + out - alias, 0)
            record.peak_memory_bytes = max(record.peak_memory_bytes, peak)
            record.argument_bytes = max(record.argument_bytes, arg)
            record.output_bytes = max(record.output_bytes, out)
            record.temp_bytes = max(record.temp_bytes, tmp)
    except Exception:
        pass


class CatalogedJit:
    """A jax.jit'd callable enrolled in the catalog.

    First call per input signature compiles through the AOT path
    (`fn.lower(*args).compile()`) — the SAME one backend compile the
    plain call would have cost — keeps the `Compiled` executable, and
    reads its cost/memory analyses into the program record. Subsequent
    calls invoke the captured executable directly and account
    invocations + host wall time. Any AOT failure (exotic backend,
    unhashable signature) falls back to the plain jitted call for that
    signature; the record then carries counts without analysis.
    """

    def __init__(self, catalog: 'ProgramCatalog', fn, name: Optional[str]
                 = None, name_fn: Optional[Callable] = None,
                 kind: str = 'jit'):
        if name is None and name_fn is None:
            raise ValueError('CatalogedJit needs name= or name_fn=')
        self._catalog = catalog
        self._fn = fn
        self._name = name
        self._name_fn = name_fn
        self._kind = kind
        self._entries: Dict[Any, Any] = {}   # sig -> (record, callable)

    def _signature(self, args):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            dt = getattr(leaf, 'dtype', None)
            if dt is not None:
                sig.append((tuple(getattr(leaf, 'shape', ())), str(dt),
                            bool(getattr(leaf, 'weak_type', False))))
            else:
                sig.append(('py', type(leaf)))
        key = (treedef, tuple(sig))
        hash(key)
        return key

    def _build(self, key, args):
        if self._name is not None:
            name = self._name
        else:
            try:
                name = self._name_fn(args)
            except Exception:
                name = f'{self._kind}:unnamed'   # naming must never fail a call
        record = self._catalog.record(name, kind=self._kind)
        call = self._fn
        if key is not None:
            t0 = time.perf_counter()
            try:
                compiled = self._fn.lower(*args).compile()
                dt = time.perf_counter() - t0
                with self._catalog._lock:
                    record.compile_count += 1
                    record.compile_seconds += dt
                _read_analysis(compiled, record)
                call = compiled
            except Exception:
                # AOT path unavailable here: serve through the plain
                # jitted call — counts still accumulate, analysis stays
                # empty and the report marks it
                record.note = 'aot_unavailable'
            self._entries[key] = (record, call)
        return record, call

    def __call__(self, *args):
        try:
            key = self._signature(args)
        except Exception:
            key = None
        entry = self._entries.get(key) if key is not None else None
        t0 = time.perf_counter()
        if entry is None:
            record, call = self._build(key, args)
        else:
            record, call = entry
        out = call(*args)
        dt = time.perf_counter() - t0
        with self._catalog._lock:
            record.invocations += 1
            record.host_seconds += dt
        return out

    # the wrapped object still answers AOT introspection (TrainStep's
    # memory_analysis does `self._jitted.lower(...)`); the lowering
    # cache makes that free after the wrapper's own compile
    def __getattr__(self, name):
        return getattr(self._fn, name)


class ProgramCatalog:
    """Registry of every named compiled program in the process."""

    def __init__(self):
        self._lock = threading.RLock()
        self._records: Dict[str, ProgramRecord] = {}

    # -- enrollment ---------------------------------------------------------
    def record(self, name: str, kind: str = 'jit') -> ProgramRecord:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = ProgramRecord(name, kind)
            return rec

    def wrap_jit(self, fn, name: Optional[str] = None,
                 name_fn: Optional[Callable] = None,
                 kind: str = 'jit', statics: Any = None,
                 persist: bool = True):
        """Enroll a jax.jit'd callable; returns the drop-in wrapper.

        Since the program-store consolidation this delegates to
        `paddle_tpu.programs.ProgramStore.wrap_jit` — the store owns
        compilation (and the persistent tier); THIS catalog stays the
        bookkeeping, so every program is tracked exactly once. A
        catalog that is not the store's own (tests constructing a
        private one) keeps the legacy in-wrapper AOT path."""
        from ..programs import get_store
        store = get_store()
        if store.catalog is self:
            return store.wrap_jit(fn, name=name, name_fn=name_fn,
                                  kind=kind, statics=statics,
                                  persist=persist)
        return CatalogedJit(self, fn, name=name, name_fn=name_fn, kind=kind)

    def note_invocation(self, name: str, seconds: float = 0.0, n: int = 1,
                        kind: str = 'jit'):
        rec = self.record(name, kind)
        with self._lock:
            rec.invocations += n
            rec.host_seconds += seconds
        return rec

    def note_compile(self, name: str, seconds: float, kind: str = 'jit'):
        rec = self.record(name, kind)
        with self._lock:
            rec.compile_count += 1
            rec.compile_seconds += seconds
        return rec

    # -- dispatch-cache mirror ----------------------------------------------
    def _sync_dispatch(self):
        """Mirror the eager dispatch cache's per-op call counts into
        `eager:{op}` records (compile times arrive from the cache's own
        cold miss path via `note_dispatch_compile`). Mirrors, not
        accumulates — runs at report/scrape time only."""
        try:
            from .. import _dispatch
            per_op = _dispatch.stats()['per_op']
        except Exception:
            return
        with self._lock:
            for op, row in per_op.items():
                rec = self.record(f'eager:{op}', kind='dispatch')
                rec.invocations = row['hits'] + row['misses']

    # -- reporting ----------------------------------------------------------
    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.values())

    def top_programs(self, n: int = 10, sort_by: str = 'host_seconds',
                     kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The attribution report: programs ranked by `sort_by`
        ('host_seconds', 'flops', 'bytes_accessed', 'invocations',
        'compile_seconds'). Pure dict reads — never compiles."""
        self._sync_dispatch()
        rows = [r.as_dict() for r in self.records()
                if kind is None or r.kind == kind]
        rows.sort(key=lambda r: (-r.get(sort_by, 0.0), r['name']))
        return rows[:n]

    def snapshot(self) -> Dict[str, Any]:
        self._sync_dispatch()
        return {'programs': [r.as_dict() for r in self.records()]}

    def report(self, max_rows: int = 12) -> str:
        """Human-readable program-attribution table."""
        rows = self.top_programs(n=max_rows)
        lines = [f'program catalog: {len(self.records())} program(s)',
                 f'  {"program":<28}{"kind":<10}{"calls":>8}'
                 f'{"host s":>10}{"compile s":>10}{"GFLOPs":>10}'
                 f'{"GB moved":>10}{"peak MiB":>10}']
        for r in rows:
            lines.append(
                f'  {r["name"][:27]:<28}{r["kind"]:<10}'
                f'{r["invocations"]:>8}'
                f'{r["host_seconds"]:>10.3f}'
                f'{r["compile_seconds"]:>10.3f}'
                f'{r["flops"] / 1e9:>10.3f}'
                f'{r["bytes_accessed"] / 1e9:>10.3f}'
                f'{r["peak_memory_bytes"] / 2**20:>10.1f}')
        return '\n'.join(lines)

    def reset(self):
        with self._lock:
            self._records.clear()


_catalog = ProgramCatalog()


def get_catalog() -> ProgramCatalog:
    return _catalog


def note_dispatch_compile(op_name: str, seconds: float):
    """Cold-path hook for paddle_tpu._dispatch: one cache entry was
    traced+compiled (the building call's wall time)."""
    _catalog.note_compile(f'eager:{op_name}', seconds, kind='dispatch')


def _program_collector(reg: '_metrics.MetricsRegistry'):
    """Scrape-time mirror of the catalog into `paddle_program_*`
    metrics (mirror, not accumulate — same contract as the dispatch
    collector)."""
    cat = _catalog
    cat._sync_dispatch()
    inv = reg.counter('paddle_program_invocations_total',
                      'compiled-program invocations', ('program',))
    host = reg.counter('paddle_program_host_seconds_total',
                       'host wall seconds inside compiled programs',
                       ('program',))
    comp = reg.counter('paddle_program_compile_seconds_total',
                       'seconds compiling each program', ('program',))
    flops = reg.gauge('paddle_program_flops',
                      'XLA cost_analysis FLOPs per invocation',
                      ('program',))
    byts = reg.gauge('paddle_program_bytes_accessed',
                     'XLA cost_analysis bytes accessed per invocation',
                     ('program',))
    peak = reg.gauge('paddle_program_peak_memory_bytes',
                     'XLA memory_analysis peak bytes', ('program',))
    for r in cat.records():
        inv.labels(program=r.name).value = float(r.invocations)
        host.labels(program=r.name).value = float(r.host_seconds)
        comp.labels(program=r.name).value = float(r.compile_seconds)
        flops.labels(program=r.name).set(r.flops)
        byts.labels(program=r.name).set(r.bytes_accessed)
        peak.labels(program=r.name).set(r.peak_memory_bytes)


def install(registry: Optional['_metrics.MetricsRegistry'] = None):
    """Idempotent: register the scrape-time program collector."""
    (registry or _metrics.get_registry()).register_collector(
        _program_collector)
