"""Per-program XLA cost attribution: the ProgramCatalog.

Every compiled executable this framework creates — the jitted train
step, to_static programs, the serving engine's decode block and
per-bucket prefills, the eager dispatch cache's per-op entries — already
carries free introspection data XLA computes at compile time
(`compiled.cost_analysis()` FLOPs / bytes accessed,
`compiled.memory_analysis()` peak HBM) that we previously threw away.
The catalog records it per *named program* together with compile time,
cumulative invocation count, and host wall time, so
`top_programs()` answers "which programs is this step/decode round
actually spending its time and FLOPs in" — train step vs. decode block
vs. prefill buckets — without a profiler attached.

Zero extra compiles by construction: `wrap_jit` compiles a jitted
callable ONCE through the AOT path (`lower().compile()`) per input
signature and then invokes the captured `Compiled` object directly, so
the cost/memory analyses are read off the very executable that serves
the traffic — the catalog never compiles anything the program would not
have compiled anyway (guarded by the serving zero-recompile tests over
`paddle_jit_compiles_total`). Since the program-store consolidation
(`paddle_tpu.programs`), compilation itself is owned by the store —
`wrap_jit` delegates there, THIS catalog remains the bookkeeping, and
every program is tracked exactly once (tier-1 catalog==store guard).

Hot paths never pay: the eager dispatch cache reports only from its
cold miss path (`note_dispatch_compile`) and its per-op invocation
counts are mirrored at scrape time by a registry collector, exactly
like the `paddle_dispatch_*` metrics.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

# ---------------------------------------------------------------------------
# roofline peaks: per-device-kind peak bf16 FLOP/s + HBM bandwidth.
# Public TPU spec-sheet numbers (same table bench.py's MFU headline
# uses); keyed by substring of jax's `device_kind`. Override per
# deployment with PADDLE_PEAK_FLOPS (FLOP/s) / PADDLE_PEAK_HBM_GBPS
# (GB/s) — the only honest path on CPU or unlisted hardware, where the
# fallback is an explicit 'unknown' (no MFU published) rather than a
# silently-wrong guess.
# ---------------------------------------------------------------------------
PEAK_SPECS: Dict[str, Dict[str, float]] = {
    'v6 lite': {'flops': 918e12, 'hbm_gbps': 1640.0},
    'v6e': {'flops': 918e12, 'hbm_gbps': 1640.0},
    'v5 lite': {'flops': 197e12, 'hbm_gbps': 819.0},
    'v5e': {'flops': 197e12, 'hbm_gbps': 819.0},
    'v5p': {'flops': 459e12, 'hbm_gbps': 2765.0},
    'v5': {'flops': 459e12, 'hbm_gbps': 2765.0},
    'v4': {'flops': 275e12, 'hbm_gbps': 1228.0},
    'v3': {'flops': 123e12, 'hbm_gbps': 900.0},
    'v2': {'flops': 45e12, 'hbm_gbps': 700.0},
}


def device_peaks(device=None) -> Dict[str, Any]:
    """Resolve the roofline peaks for `device` (default: devices()[0]).

    Returns {'device_kind', 'peak_flops', 'peak_hbm_bytes_per_s',
    'source'} where source is 'env' (operator override), 'table'
    (PEAK_SPECS match), or 'unknown' (peaks are None — MFU/roofline
    gauges are NOT published rather than normalized against a guess)."""
    kind = ''
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:  # paddle-lint: disable=swallowed-exception -- device probe; kind stays unknown and no MFU gauge is published against a guess
            device = None
    if device is not None:
        kind = str(getattr(device, 'device_kind', '') or '')
    env_flops = os.environ.get('PADDLE_PEAK_FLOPS')
    env_bw = os.environ.get('PADDLE_PEAK_HBM_GBPS')
    if env_flops:
        try:
            return {'device_kind': kind or 'env-override',
                    'peak_flops': float(env_flops),
                    'peak_hbm_bytes_per_s': (float(env_bw) * 1e9
                                             if env_bw else None),
                    'source': 'env'}
        except ValueError:
            pass   # malformed override falls through to the table
    low = kind.lower()
    for key, spec in PEAK_SPECS.items():
        if key in low:
            return {'device_kind': kind, 'peak_flops': spec['flops'],
                    'peak_hbm_bytes_per_s': spec['hbm_gbps'] * 1e9,
                    'source': 'table'}
    return {'device_kind': kind or 'unknown', 'peak_flops': None,
            'peak_hbm_bytes_per_s': None, 'source': 'unknown'}


def _ledger_window() -> 'Tuple[Optional[float], Dict[str, int]]':
    """The goodput ledger's measurement window: (wall seconds since the
    ledger's last reset, per-program invocation baseline captured at
    that reset). MFU is FLOPs-over-WALL — per-call host timing cannot
    see device time under async dispatch (a call returns in
    microseconds while the chip works for milliseconds), so the only
    honest denominator is the wall clock of a window whose invocation
    counts we also know."""
    try:
        from .goodput import get_ledger
        return get_ledger().mfu_window()
    except Exception:  # paddle-lint: disable=swallowed-exception -- ledger optional; (None, {}) window disables MFU rather than faking it
        return None, {}


def record_roofline(rec: 'ProgramRecord',
                    peaks: Optional[Dict[str, Any]] = None,
                    wall_seconds: Optional[float] = None,
                    baseline: Optional[Dict[str, int]] = None
                    ) -> Dict[str, Any]:
    """MFU contribution + roofline classification for one program.

    mfu = (per-invocation cost_analysis FLOPs x invocations in the
    window) / (window WALL seconds) / peak FLOP/s — the program's
    contribution to machine utilization, PaLM-style: per-program MFUs
    sum to the aggregate, and every overhead second (compile,
    checkpoint, backoff — the goodput ledger's categories) shows up as
    MFU lost, not hidden. Roofline bound compares the program's
    arithmetic intensity (FLOPs / bytes accessed) with the machine
    balance (peak FLOPs / peak bandwidth): below the ridge the program
    cannot be compute-bound no matter how good the kernels are. Fields
    are None when the record has no analysis or the device peaks are
    unknown. The window defaults to the goodput ledger's (wall since
    its last reset; invocation baseline captured there)."""
    peaks = peaks or device_peaks()
    if wall_seconds is None:
        wall_seconds, baseline = _ledger_window()
    baseline = baseline or {}
    out = {'mfu': None, 'roofline_bound': None,
           'arithmetic_intensity': None}
    if rec.flops > 0 and rec.bytes_accessed > 0:
        out['arithmetic_intensity'] = rec.flops / rec.bytes_accessed
    pf, pb = peaks['peak_flops'], peaks['peak_hbm_bytes_per_s']
    if pf and rec.flops > 0 and wall_seconds and wall_seconds > 0:
        d_inv = rec.invocations - baseline.get(rec.name, 0)
        if d_inv > 0:
            out['mfu'] = rec.flops * d_inv / wall_seconds / pf
    if pf and pb and out['arithmetic_intensity'] is not None:
        balance = pf / pb
        out['roofline_bound'] = ('compute'
                                 if out['arithmetic_intensity'] >= balance
                                 else 'bandwidth')
    return out


def aggregate_mfu(records: List['ProgramRecord'],
                  peaks: Optional[Dict[str, Any]] = None,
                  wall_seconds: Optional[float] = None,
                  baseline: Optional[Dict[str, int]] = None
                  ) -> Dict[str, Any]:
    """Aggregate MFU: total model FLOPs executed in the window / window
    WALL seconds / peak — the number bench.py's headline derives
    analytically, here measured off XLA's own cost_analysis. Programs
    without cost analysis contribute nothing (their time is invisible
    to MFU, which the goodput ledger's residual makes loud instead)."""
    peaks = peaks or device_peaks()
    if wall_seconds is None:
        wall_seconds, baseline = _ledger_window()
    baseline = baseline or {}
    flops = sum(r.flops * max(r.invocations - baseline.get(r.name, 0), 0)
                for r in records if r.flops > 0)
    out = {'flops_total': flops, 'wall_seconds': wall_seconds,
           'mfu': None, 'peaks': peaks}
    if peaks['peak_flops'] and wall_seconds and wall_seconds > 0:
        out['mfu'] = flops / wall_seconds / peaks['peak_flops']
    return out


class MfuWindow:
    """Bounded MFU measurement: wall clock + per-program invocation
    counts snapshot at `__enter__`, deltas at `result()` — the same
    FLOPs-over-wall estimator as `paddle_mfu`, but over exactly the
    code between enter and result (the bench goodput phase runs its
    timed GPT loop inside one and cross-checks the analytic MFU)."""

    def __init__(self, catalog: Optional['ProgramCatalog'] = None,
                 peaks: Optional[Dict[str, Any]] = None):
        # `is None`: an empty ProgramCatalog must not be swapped out
        self._catalog = catalog if catalog is not None else get_catalog()
        self._peaks = peaks or device_peaks()
        self._before: Dict[str, int] = {}
        self._t0 = 0.0

    def __enter__(self) -> 'MfuWindow':
        self._before = {r.name: r.invocations
                        for r in self._catalog.records()}
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        pass

    def result(self) -> Dict[str, Any]:
        wall = time.perf_counter() - self._t0
        return aggregate_mfu(self._catalog.records(), self._peaks,
                             wall_seconds=wall, baseline=self._before)


class ProgramRecord:
    """One named compiled program's cumulative accounting."""

    __slots__ = ('name', 'kind', 'compile_count', 'compile_seconds',
                 'invocations', 'host_seconds', 'flops', 'bytes_accessed',
                 'peak_memory_bytes', 'argument_bytes', 'output_bytes',
                 'temp_bytes', 'analyzed', 'note')

    def __init__(self, name: str, kind: str = 'jit'):
        self.name = name
        self.kind = kind
        self.compile_count = 0
        self.compile_seconds = 0.0
        self.invocations = 0
        self.host_seconds = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.peak_memory_bytes = 0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.analyzed = False
        self.note = ''

    def as_dict(self) -> Dict[str, Any]:
        return {
            'name': self.name, 'kind': self.kind,
            'compile_count': self.compile_count,
            'compile_seconds': self.compile_seconds,
            'invocations': self.invocations,
            'host_seconds': self.host_seconds,
            'flops': self.flops, 'bytes_accessed': self.bytes_accessed,
            'peak_memory_bytes': self.peak_memory_bytes,
            'argument_bytes': self.argument_bytes,
            'output_bytes': self.output_bytes,
            'temp_bytes': self.temp_bytes,
            'analyzed': self.analyzed, 'note': self.note,
        }


def _read_analysis(compiled, record: ProgramRecord):
    """Fill a record from a jax `Compiled` object's free introspection.
    Cumulative across signatures: a program recompiled at a second
    shape (to_static buckets) keeps the LARGEST figures — the report
    attributes the expensive variant."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            record.flops = max(record.flops, float(ca.get('flops', 0.0)))
            record.bytes_accessed = max(
                record.bytes_accessed, float(ca.get('bytes accessed', 0.0)))
            record.analyzed = True
    except Exception:  # paddle-lint: disable=swallowed-exception -- cost_analysis unavailable on this backend; record.analyzed stays False and the report marks it
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, 'argument_size_in_bytes', 0) or 0)
            out = int(getattr(ma, 'output_size_in_bytes', 0) or 0)
            tmp = int(getattr(ma, 'temp_size_in_bytes', 0) or 0)
            alias = int(getattr(ma, 'alias_size_in_bytes', 0) or 0)
            peak = int(getattr(ma, 'peak_memory_in_bytes', 0) or 0)
            if not peak:
                # CPU/older backends report no live peak: the resident
                # footprint bound is args + temps + outputs - aliased
                peak = max(arg + tmp + out - alias, 0)
            record.peak_memory_bytes = max(record.peak_memory_bytes, peak)
            record.argument_bytes = max(record.argument_bytes, arg)
            record.output_bytes = max(record.output_bytes, out)
            record.temp_bytes = max(record.temp_bytes, tmp)
    except Exception:  # paddle-lint: disable=swallowed-exception -- memory_analysis unavailable on this backend; record fields stay 0 and the report marks it
        pass


class CatalogedJit:
    """A jax.jit'd callable enrolled in the catalog.

    First call per input signature compiles through the AOT path
    (`fn.lower(*args).compile()`) — the SAME one backend compile the
    plain call would have cost — keeps the `Compiled` executable, and
    reads its cost/memory analyses into the program record. Subsequent
    calls invoke the captured executable directly and account
    invocations + host wall time. Any AOT failure (exotic backend,
    unhashable signature) falls back to the plain jitted call for that
    signature; the record then carries counts without analysis.
    """

    def __init__(self, catalog: 'ProgramCatalog', fn, name: Optional[str]
                 = None, name_fn: Optional[Callable] = None,
                 kind: str = 'jit'):
        if name is None and name_fn is None:
            raise ValueError('CatalogedJit needs name= or name_fn=')
        self._catalog = catalog
        self._fn = fn
        self._name = name
        self._name_fn = name_fn
        self._kind = kind
        self._entries: Dict[Any, Any] = {}   # sig -> (record, callable)

    def _signature(self, args):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            dt = getattr(leaf, 'dtype', None)
            if dt is not None:
                sig.append((tuple(getattr(leaf, 'shape', ())), str(dt),
                            bool(getattr(leaf, 'weak_type', False))))
            else:
                sig.append(('py', type(leaf)))
        key = (treedef, tuple(sig))
        hash(key)
        return key

    def _build(self, key, args):
        if self._name is not None:
            name = self._name
        else:
            try:
                name = self._name_fn(args)
            except Exception:  # paddle-lint: disable=swallowed-exception -- naming must never fail a call; kind:unnamed IS the visible trace
                name = f'{self._kind}:unnamed'   # naming must never fail a call
        record = self._catalog.record(name, kind=self._kind)
        call = self._fn
        if key is not None:
            t0 = time.perf_counter()
            try:
                compiled = self._fn.lower(*args).compile()
                dt = time.perf_counter() - t0
                with self._catalog._lock:
                    record.compile_count += 1
                    record.compile_seconds += dt
                _read_analysis(compiled, record)
                call = compiled
            except Exception:  # paddle-lint: disable=swallowed-exception -- AOT path unavailable; record.note=aot_unavailable carries the posture into every report
                # AOT path unavailable here: serve through the plain
                # jitted call — counts still accumulate, analysis stays
                # empty and the report marks it
                record.note = 'aot_unavailable'
            self._entries[key] = (record, call)
        return record, call

    def __call__(self, *args):
        try:
            key = self._signature(args)
        except Exception:
            _metrics.count_suppressed('catalog.signature')
            key = None
        entry = self._entries.get(key) if key is not None else None
        t0 = time.perf_counter()
        if entry is None:
            record, call = self._build(key, args)
        else:
            record, call = entry
        out = call(*args)
        dt = time.perf_counter() - t0
        with self._catalog._lock:
            record.invocations += 1
            record.host_seconds += dt
        return out

    # the wrapped object still answers AOT introspection (TrainStep's
    # memory_analysis does `self._jitted.lower(...)`); the lowering
    # cache makes that free after the wrapper's own compile
    def __getattr__(self, name):
        return getattr(self._fn, name)


class ProgramCatalog:
    """Registry of every named compiled program in the process."""

    def __init__(self):
        self._lock = _concurrency.RLock('ProgramCatalog._lock')
        self._records: Dict[str, ProgramRecord] = {}

    # -- enrollment ---------------------------------------------------------
    def record(self, name: str, kind: str = 'jit') -> ProgramRecord:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = ProgramRecord(name, kind)
            return rec

    def wrap_jit(self, fn, name: Optional[str] = None,
                 name_fn: Optional[Callable] = None,
                 kind: str = 'jit', statics: Any = None,
                 persist: bool = True):
        """Enroll a jax.jit'd callable; returns the drop-in wrapper.

        Since the program-store consolidation this delegates to
        `paddle_tpu.programs.ProgramStore.wrap_jit` — the store owns
        compilation (and the persistent tier); THIS catalog stays the
        bookkeeping, so every program is tracked exactly once. A
        catalog that is not the store's own (tests constructing a
        private one) keeps the legacy in-wrapper AOT path."""
        from ..programs import get_store
        store = get_store()
        if store.catalog is self:
            return store.wrap_jit(fn, name=name, name_fn=name_fn,
                                  kind=kind, statics=statics,
                                  persist=persist)
        return CatalogedJit(self, fn, name=name, name_fn=name_fn, kind=kind)

    def note_invocation(self, name: str, seconds: float = 0.0, n: int = 1,
                        kind: str = 'jit'):
        rec = self.record(name, kind)
        with self._lock:
            rec.invocations += n
            rec.host_seconds += seconds
        return rec

    def note_compile(self, name: str, seconds: float, kind: str = 'jit'):
        rec = self.record(name, kind)
        with self._lock:
            rec.compile_count += 1
            rec.compile_seconds += seconds
        return rec

    # -- dispatch-cache mirror ----------------------------------------------
    def _sync_dispatch(self):
        """Mirror the eager dispatch cache's per-op call counts into
        `eager:{op}` records (compile times arrive from the cache's own
        cold miss path via `note_dispatch_compile`). Mirrors, not
        accumulates — runs at report/scrape time only."""
        try:
            from .. import _dispatch
            per_op = _dispatch.stats()['per_op']
        except Exception:  # paddle-lint: disable=swallowed-exception -- dispatch cache absent: nothing to mirror at scrape time
            return
        with self._lock:
            for op, row in per_op.items():
                rec = self.record(f'eager:{op}', kind='dispatch')
                rec.invocations = row['hits'] + row['misses']

    # -- reporting ----------------------------------------------------------
    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.values())

    def top_programs(self, n: int = 10, sort_by: str = 'host_seconds',
                     kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The attribution report: programs ranked by `sort_by`
        ('host_seconds', 'flops', 'bytes_accessed', 'invocations',
        'compile_seconds', 'mfu'). Every row carries the roofline view
        — 'mfu', 'roofline_bound' ('compute'|'bandwidth'), and
        'arithmetic_intensity' — None where the device peaks are
        unknown or the program has no cost analysis. Pure dict reads —
        never compiles."""
        self._sync_dispatch()
        peaks = device_peaks()
        wall, baseline = _ledger_window()
        rows = []
        for r in self.records():
            if kind is not None and r.kind != kind:
                continue
            row = r.as_dict()
            row.update(record_roofline(r, peaks, wall, baseline))
            rows.append(row)
        rows.sort(key=lambda r: (-(r.get(sort_by) or 0.0), r['name']))
        return rows[:n]

    def snapshot(self) -> Dict[str, Any]:
        self._sync_dispatch()
        return {'programs': [r.as_dict() for r in self.records()]}

    def report(self, max_rows: int = 12) -> str:
        """Human-readable program-attribution table."""
        rows = self.top_programs(n=max_rows)
        peaks = device_peaks()
        wall, baseline = _ledger_window()
        agg = aggregate_mfu(self.records(), peaks, wall, baseline)
        head = f'program catalog: {len(self.records())} program(s)'
        if agg['mfu'] is not None:
            head += (f'  aggregate MFU {agg["mfu"]:.3f} '
                     f'({peaks["device_kind"]}, peak '
                     f'{peaks["peak_flops"] / 1e12:.0f} TFLOP/s, '
                     f'{peaks["source"]})')
        else:
            head += (f'  MFU unknown (device {peaks["device_kind"]!r} '
                     f'not in peak table; set PADDLE_PEAK_FLOPS)')
        lines = [head,
                 f'  {"program":<28}{"kind":<10}{"calls":>8}'
                 f'{"host s":>10}{"compile s":>10}{"GFLOPs":>10}'
                 f'{"GB moved":>10}{"peak MiB":>10}{"mfu":>7}'
                 f'{"bound":>11}']
        for r in rows:
            mfu = f'{r["mfu"]:.3f}' if r['mfu'] is not None else '-'
            bound = r['roofline_bound'] or '-'
            lines.append(
                f'  {r["name"][:27]:<28}{r["kind"]:<10}'
                f'{r["invocations"]:>8}'
                f'{r["host_seconds"]:>10.3f}'
                f'{r["compile_seconds"]:>10.3f}'
                f'{r["flops"] / 1e9:>10.3f}'
                f'{r["bytes_accessed"] / 1e9:>10.3f}'
                f'{r["peak_memory_bytes"] / 2**20:>10.1f}'
                f'{mfu:>7}{bound:>11}')
        return '\n'.join(lines)

    def reset(self):
        with self._lock:
            self._records.clear()


_catalog = ProgramCatalog()


def get_catalog() -> ProgramCatalog:
    return _catalog


def roofline_summary(max_rows: int = 5) -> Dict[str, Any]:
    """The /summary roofline section: device peaks (+ how they were
    resolved), aggregate MFU, per-bound program counts, and the top
    analyzed programs by MFU-weighted host time."""
    peaks = device_peaks()
    wall, baseline = _ledger_window()
    records = _catalog.records()
    agg = aggregate_mfu(records, peaks, wall, baseline)
    rows = []
    for r in records:
        roof = record_roofline(r, peaks, wall, baseline)
        if roof['mfu'] is None:
            continue
        rows.append({'name': r.name, 'host_seconds': r.host_seconds,
                     'mfu': roof['mfu'],
                     'bound': roof['roofline_bound'],
                     'intensity': roof['arithmetic_intensity']})
    rows.sort(key=lambda r: -r['mfu'])
    bounds = {'compute': 0, 'bandwidth': 0}
    for r in rows:
        if r['bound'] in bounds:
            bounds[r['bound']] += 1
    return {'device_kind': peaks['device_kind'],
            'peak_flops': peaks['peak_flops'],
            'peak_hbm_bytes_per_s': peaks['peak_hbm_bytes_per_s'],
            'source': peaks['source'],
            'mfu': agg['mfu'],
            'flops_total': agg['flops_total'],
            'window_wall_seconds': agg['wall_seconds'],
            'bound_counts': bounds,
            'programs': rows[:max_rows]}


def note_dispatch_compile(op_name: str, seconds: float):
    """Cold-path hook for paddle_tpu._dispatch: one cache entry was
    traced+compiled (the building call's wall time)."""
    _catalog.note_compile(f'eager:{op_name}', seconds, kind='dispatch')


def _program_collector(reg: '_metrics.MetricsRegistry'):
    """Scrape-time mirror of the catalog into `paddle_program_*`
    metrics (mirror, not accumulate — same contract as the dispatch
    collector)."""
    cat = _catalog
    cat._sync_dispatch()
    inv = reg.counter('paddle_program_invocations_total',
                      'compiled-program invocations', ('program',))
    host = reg.counter('paddle_program_host_seconds_total',
                       'host wall seconds inside compiled programs',
                       ('program',))
    comp = reg.counter('paddle_program_compile_seconds_total',
                       'seconds compiling each program', ('program',))
    flops = reg.gauge('paddle_program_flops',
                      'XLA cost_analysis FLOPs per invocation',
                      ('program',))
    byts = reg.gauge('paddle_program_bytes_accessed',
                     'XLA cost_analysis bytes accessed per invocation',
                     ('program',))
    peak = reg.gauge('paddle_program_peak_memory_bytes',
                     'XLA memory_analysis peak bytes', ('program',))
    pmfu = reg.gauge('paddle_program_mfu',
                     'model-FLOPs utilization per program '
                     '(cost_analysis FLOPs / host seconds / device peak)',
                     ('program',))
    bound = reg.gauge(
        'paddle_roofline_bound',
        'programs on each side of the roofline ridge '
        '(arithmetic intensity vs machine balance)', ('bound',))
    agg = reg.gauge('paddle_mfu',
                    'aggregate model-FLOPs utilization across analyzed '
                    'programs (0 while device peaks are unknown)')
    peaks = device_peaks()
    wall, baseline = _ledger_window()
    counts = {'compute': 0, 'bandwidth': 0}
    records = cat.records()
    for r in records:
        inv.labels(program=r.name).value = float(r.invocations)
        host.labels(program=r.name).value = float(r.host_seconds)
        comp.labels(program=r.name).value = float(r.compile_seconds)
        flops.labels(program=r.name).set(r.flops)
        byts.labels(program=r.name).set(r.bytes_accessed)
        peak.labels(program=r.name).set(r.peak_memory_bytes)
        roof = record_roofline(r, peaks, wall, baseline)
        if roof['mfu'] is not None:
            pmfu.labels(program=r.name).set(roof['mfu'])
        if roof['roofline_bound'] is not None:
            counts[roof['roofline_bound']] += 1
    for b, n in counts.items():
        bound.labels(bound=b).set(n)
    a = aggregate_mfu(records, peaks, wall, baseline)
    agg.set(a['mfu'] if a['mfu'] is not None else 0.0)


def install(registry: Optional['_metrics.MetricsRegistry'] = None):
    """Idempotent: register the scrape-time program collector."""
    reg = registry if registry is not None else _metrics.get_registry()
    reg.register_collector(_program_collector)
