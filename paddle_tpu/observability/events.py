"""Structured event log + span tracing with real timestamps.

Upstream analogue: paddle.profiler's RecordEvent host regions and the
fleet loss-spike logs — here unified as one bounded in-process
`EventLog` of JSON-able events carrying *actual* begin timestamps and
durations (not fabricated running sums), so the chrome-trace export is a
true timeline and JSONL tailing works for long fleet runs.

`span(name, **attrs)` is the tracing API every subsystem uses: a context
manager that records perf_counter begin/end, nesting depth, and thread
id into the event log and a `paddle_span_seconds{name}` histogram in the
metrics registry. `emit(name, **attrs)` records an instant event (e.g.
`loss_spike` from debug.LossSpikeDetector).
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

# one process-wide clock origin so event timestamps from every thread /
# subsystem land on a single comparable timeline
_EPOCH = time.perf_counter()


def _now() -> float:
    return time.perf_counter() - _EPOCH


class EventLog:
    """Bounded, thread-safe ring of structured events (oldest dropped)."""

    def __init__(self, capacity: int = 4096):
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._listeners: List = []

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    @property
    def dropped(self) -> int:
        return self._dropped

    def append(self, event: Dict[str, Any]):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)
        # listeners run OUTSIDE the lock (a listener may read the log,
        # e.g. the flight recorder dumping on an anomaly event)
        for fn in list(self._listeners):
            try:
                fn(event)
            except Exception:
                pass   # a broken listener must not break emit sites

    def add_listener(self, fn):
        """`fn(event)` runs after every append (anomaly triggers)."""
        if fn not in self._listeners:
            self._listeners.append(fn)
        return fn

    def remove_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def emit(self, name: str, **attrs):
        """Record an instant (zero-duration) event at the current time."""
        if not _metrics.enabled():
            return
        self.append({'name': name, 'ph': 'i', 'ts': _now(),
                     'tid': threading.get_ident(), 'attrs': attrs})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def __len__(self):
        return len(self._events)

    def to_jsonl(self, path: Optional[str] = None) -> str:
        text = '\n'.join(json.dumps(e) for e in self.events())
        if text:
            text += '\n'
        if path is not None:
            with open(path, 'w') as f:
                f.write(text)
        return text

    def to_chrome_trace(self, path: Optional[str] = None) -> Dict[str, Any]:
        from .exporters import to_chrome_trace
        return to_chrome_trace(self, path)


_default_log = EventLog()


def get_event_log() -> EventLog:
    return _default_log


@_metrics.get_registry().register_collector
def _dropped_collector(reg):
    """Scrape-time mirror: events silently aged out of the bounded ring
    are visible on /metrics, so trace truncation is never a surprise."""
    fam = reg.counter('paddle_events_dropped_total',
                      'events dropped by the bounded EventLog')
    fam._sole().value = float(_default_log.dropped)   # mirror


def emit(name: str, **attrs):
    _default_log.emit(name, **attrs)


class _SpanState(threading.local):
    def __init__(self):
        self.depth = 0


_span_state = _SpanState()


class Span:
    """Timed region recorded into the EventLog + span histogram. Nestable;
    usable as a context manager or via explicit begin()/end()."""

    __slots__ = ('name', 'attrs', '_t0', '_log', '_active')

    def __init__(self, name: str, _log: Optional[EventLog] = None, **attrs):
        self.name = name
        self.attrs = attrs
        self._log = _log or _default_log
        self._t0 = 0.0
        self._active = False

    def begin(self) -> 'Span':
        self._active = _metrics.enabled()
        if self._active:
            _span_state.depth += 1
            self._t0 = _now()
        return self

    def end(self):
        if not self._active:
            return
        self._active = False
        dur = _now() - self._t0
        depth = _span_state.depth
        _span_state.depth -= 1
        ev = {'name': self.name, 'ph': 'X', 'ts': self._t0, 'dur': dur,
              'tid': threading.get_ident(), 'depth': depth}
        if self.attrs:
            ev['attrs'] = self.attrs
        self._log.append(ev)
        _metrics.get_registry().histogram(
            'paddle_span_seconds', 'span(name) wall time',
            ('name',)).labels(name=self.name).observe(dur)

    def __enter__(self) -> 'Span':
        return self.begin()

    def __exit__(self, *exc):
        self.end()


def span(name: str, **attrs) -> Span:
    """`with span('fleet.dist_train_step', step=i): ...` — records a real
    begin/end timestamped event and a duration histogram sample."""
    return Span(name, **attrs)
