"""Structured event log + span tracing with real timestamps.

Upstream analogue: paddle.profiler's RecordEvent host regions and the
fleet loss-spike logs — here unified as one bounded in-process
`EventLog` of JSON-able events carrying *actual* begin timestamps and
durations (not fabricated running sums), so the chrome-trace export is a
true timeline and JSONL tailing works for long fleet runs.

`span(name, **attrs)` is the tracing API every subsystem uses: a context
manager that records perf_counter begin/end, nesting depth, and thread
id into the event log and a `paddle_span_seconds{name}` histogram in the
metrics registry. `emit(name, **attrs)` records an instant event (e.g.
`loss_spike` from debug.LossSpikeDetector).
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional

from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

# one process-wide clock origin so event timestamps from every thread /
# subsystem land on a single comparable timeline
_EPOCH = time.perf_counter()


def _now() -> float:
    return time.perf_counter() - _EPOCH


# ---------------------------------------------------------------------------
# declared event types: every `emit()` name the runtime may produce.
# The schema is the contract dashboards/flight-bundle consumers parse
# against, so drive-by event additions must land HERE first — a tier-1
# lint walks the source tree and fails on any emit() literal missing
# from this registry, and emit() itself counts undeclared names into
# `paddle_events_undeclared_total` so dynamic names can't slip past the
# static scan either. Span names are NOT events — they stay free-form
# (profiler RecordEvent regions carry user strings).
# ---------------------------------------------------------------------------
EVENT_SCHEMA: Dict[str, str] = {
    # debug / training anomalies
    'loss_spike': 'LossSpikeDetector flagged a step loss',
    'bad_step': 'FaultTolerantStep rolled back a NaN/spike step',
    'skip_budget_exhausted': 'bad-step skip budget exceeded; run dies',
    'hang_suspected': 'watchdog step deadline exceeded',
    'retry': 'transient error re-attempted with backoff',
    'preemption_signal': 'SIGTERM/SIGINT flagged by PreemptionHandler',
    'preempt_save': 'forced sync checkpoint on preemption',
    'checkpoint_corrupt': 'manifest checksum mismatch on restore',
    # fleet / elastic
    'fleet_init': 'mesh initialized',
    'topology_change': 'mesh rebuilt over a new device set',
    'topology_change_rejected': 'unusable device count; resize skipped',
    'device_probe_failed': 'device_source poll raised',
    # program store
    'program_cache_hit': 'program served from memory/disk tier',
    'program_cache_miss': 'program compiled fresh',
    'program_cache_reject': 'stored program found but unusable',
    'program_store_persist': 'program exported to the persistent tier',
    'program_store_persist_skipped': 'program not persistable',
    'program_store_preload': 'bulk preload completed',
    'program_store_invalidate': 'fingerprint refresh dropped entries',
    'program_store_wipe': 'persistent tier deleted on disk',
    # donation gauntlet (programs/donation.py)
    'donation_probe_ok': 'subprocess probe classified the runtime '
                         'donation-safe',
    'donation_probe_failed': 'probe found corruption/crash; store runs '
                             'undonated',
    'donation_enabled': 'store-served programs re-apply donate_argnums '
                        '(sentinel-guarded)',
    'donation_quarantined': 'corruption sentinel tripped; donation off '
                            'for this fingerprint',
    'serving_pool_recovered': 'donated decode failed mid-call; pool '
                              'rows rebuilt',
    # serving engine / router / tenancy
    'serving_request_failed': 'request failed; engine survives',
    'serving_drain_begin': 'graceful drain started',
    'serving_drain_complete': 'graceful drain finished',
    'prefix_hit': 'radix prefix-cache hit on admission',
    'prefix_evict': 'retained prefix slot reclaimed',
    # paged KV pool (serving/kv_pool.PagedSlotPool)
    'paged_cow': 'copy-on-write split of a shared KV page at admission',
    'page_pool_exhausted': 'page reservation failed after reclaiming '
                           'retention; request requeued',
    'request_shed': 'admission rejected under load shedding',
    'request_promoted': 'starvation promotion across QoS classes',
    'router_failover': 'accepted requests resubmitted to survivors',
    'router_failover_storm': 'failover budget exhausted',
    'breaker_open': 'replica circuit breaker opened',
    'breaker_half_open': 'breaker cooldown elapsed; probing',
    'breaker_closed': 'breaker probe succeeded; replica back',
    # online weight updates (trainer→serving hot-swap)
    'weight_publish': 'trainer published a weight version to the store',
    'weight_swap_begin': 'replica drain for a weight hot-swap started',
    'weight_swap_complete': 'replica rejoined on the new weight version',
    'weight_swap_failed': 'swap health gate failed; replica reverted',
    'weight_rollback': 'replica restored its previous weight version',
    'weight_version_quarantined':
        'weight version quarantined after a failed gate or load',
    'weight_writer_stale':
        'dead mid-commit weight publisher detected; marker+tmp swept',
    'rollout_iteration':
        'one serve→score→train→publish→swap turn of the rollout loop',
    # concurrency sanitizer (analysis/runtime/concurrency.py)
    'sanitizer_violation': 'runtime concurrency sanitizer report: '
                           'lock-order cycle, non-reentrant re-entry, '
                           'or lockset race',
    # goodput-driven autoscaling (serving/autoscaler.py)
    'autoscale_up': 'autoscaler provisioned a replica (warm '
                    'program-store path) and joined it to the fleet',
    'autoscale_down_begin': 'autoscaler cordoned a replica; graceful '
                            'drain toward removal started',
    'autoscale_down_complete': 'drained replica removed from the '
                               'fleet; no request dropped',
    # fleet observability plane (observability/{wire,shipper,aggregator,slo})
    'segment_shipped': 'fleet-plane telemetry segments committed to '
                       'the spool',
    'segment_quarantined': 'spool segment failed decode/sha256 '
                           'verification; renamed aside, not applied',
    'slo_breach': 'multi-window burn-rate alert fired for an SLO '
                  'objective',
    'slo_recovered': 'burn-rate alert cleared; short window cooled',
    'slo_capture': 'bounded jax.profiler capture started on breach',
    'fleet_signals_stale': 'FleetSignalSource fell back to the local '
                           'router: every per-process signal was stale',
    # process fleet runtime (serving/{supervisor,remote,replica_main})
    'replica_spawn': 'supervisor launched a replica process',
    'replica_ready': 'replica process warm-started and answering RPC',
    'replica_exit': 'replica process exited (rc + classification)',
    'replica_crash': 'replica process died uncleanly (crash or hang)',
    'replica_hang': 'heartbeat deadline exceeded on a live pid; '
                    'escalated to SIGKILL',
    'replica_restart': 'respawn scheduled with exponential backoff',
    'replica_quarantined': 'crash-looping replica circuit-broken out '
                           'of the respawn loop',
    'replica_retired': 'replica process retired through graceful drain',
    'replica_orphan_reaped': 'stale replica process from a previous '
                             'supervisor incarnation SIGKILLed',
    # multi-tenant adapter serving (serving/adapters/bank.py)
    'adapter_load': 'LoRA adapter factors written into a bank slot',
    'adapter_publish': 'adapter version committed to its weight store',
    'adapter_evict': 'zero-ref adapter slot reclaimed (LRU) for a '
                     'newcomer',
    'adapter_load_reject': 'adapter manifest failed verification; '
                           'version quarantined, bank keeps serving',
    'adapter_bank_saturated': 'adapter bank full of referenced slots; '
                              'request requeued (adapter_pinned) '
                              'instead of failed',
    # per-request latency ledger (observability/reqledger.py)
    'request_slow': 'request finished over the slow threshold '
                    '(N x the ttft_p99 SLO); carries the dominant '
                    'phase as the suspected driver',
}


def declare_event(name: str, help: str = ''):
    """Register an event type at runtime (deployment-specific emitters,
    fault-injection tests). Idempotent; returns the name."""
    EVENT_SCHEMA.setdefault(name, help or name)
    return name


class EventLog:
    """Bounded, thread-safe ring of structured events (oldest dropped)."""

    def __init__(self, capacity: int = 8192):
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = _concurrency.Lock('EventLog._lock')
        self._dropped = 0
        self._seq = 0
        self._listeners: List = []

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    @property
    def dropped(self) -> int:
        return self._dropped

    def append(self, event: Dict[str, Any]):
        with self._lock:
            # monotone per-log sequence: the /events?since= cursor that
            # survives ring eviction (timestamps alone can collide)
            self._seq += 1
            event.setdefault('seq', self._seq)
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)
        # listeners run OUTSIDE the lock (a listener may read the log,
        # e.g. the flight recorder dumping on an anomaly event)
        for fn in list(self._listeners):
            try:
                fn(event)
            except Exception:
                # a broken listener must not break emit sites — but it
                # must not break them SILENTLY either (a dead flight
                # recorder or goodput ledger looks exactly like "no
                # anomalies" otherwise)
                _metrics.count_suppressed('event_listener')

    def add_listener(self, fn):
        """`fn(event)` runs after every append (anomaly triggers)."""
        if fn not in self._listeners:
            self._listeners.append(fn)
        return fn

    def remove_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def emit(self, name: str, **attrs):
        """Record an instant (zero-duration) event at the current time.
        Undeclared names (missing from EVENT_SCHEMA) are still logged
        but counted — the runtime complement of the static source lint."""
        if not _metrics.enabled():
            return
        if name not in EVENT_SCHEMA:
            _metrics.get_registry().counter(
                'paddle_events_undeclared_total',
                'emit() calls whose event type is not in EVENT_SCHEMA',
                ('event',)).labels(event=name).inc()
        self.append({'name': name, 'ph': 'i', 'ts': _now(),
                     'tid': threading.get_ident(), 'attrs': attrs})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def __len__(self):
        return len(self._events)

    def to_jsonl(self, path: Optional[str] = None) -> str:
        text = '\n'.join(json.dumps(e) for e in self.events())
        if text:
            text += '\n'
        if path is not None:
            with open(path, 'w') as f:
                f.write(text)
        return text

    def to_chrome_trace(self, path: Optional[str] = None) -> Dict[str, Any]:
        from .exporters import to_chrome_trace
        return to_chrome_trace(self, path)


_default_log = EventLog()


def get_event_log() -> EventLog:
    return _default_log


@_metrics.get_registry().register_collector
def _dropped_collector(reg):
    """Scrape-time mirror: events silently aged out of the bounded ring
    are visible on /metrics, so trace truncation is never a surprise."""
    fam = reg.counter('paddle_events_dropped_total',
                      'events dropped by the bounded EventLog')
    fam._sole().value = float(_default_log.dropped)   # mirror


def emit(name: str, **attrs):
    _default_log.emit(name, **attrs)


class _SpanState(threading.local):
    def __init__(self):
        self.depth = 0


_span_state = _SpanState()


class Span:
    """Timed region recorded into the EventLog + span histogram. Nestable;
    usable as a context manager or via explicit begin()/end()."""

    __slots__ = ('name', 'attrs', '_t0', '_log', '_active')

    def __init__(self, name: str, _log: Optional[EventLog] = None, **attrs):
        self.name = name
        self.attrs = attrs
        # `is None`, not truthiness: an EMPTY EventLog is falsy
        # (__len__ == 0) and `or` would silently reroute the span to
        # the default log
        self._log = _default_log if _log is None else _log
        self._t0 = 0.0
        self._active = False

    def begin(self) -> 'Span':
        self._active = _metrics.enabled()
        if self._active:
            _span_state.depth += 1
            self._t0 = _now()
        return self

    def end(self):
        if not self._active:
            return
        self._active = False
        dur = _now() - self._t0
        depth = _span_state.depth
        _span_state.depth -= 1
        ev = {'name': self.name, 'ph': 'X', 'ts': self._t0, 'dur': dur,
              'tid': threading.get_ident(), 'depth': depth}
        if self.attrs:
            ev['attrs'] = self.attrs
        self._log.append(ev)
        _metrics.get_registry().histogram(
            'paddle_span_seconds', 'span(name) wall time',
            ('name',)).labels(name=self.name).observe(dur)

    def __enter__(self) -> 'Span':
        return self.begin()

    def __exit__(self, *exc):
        self.end()


def span(name: str, **attrs) -> Span:
    """`with span('fleet.dist_train_step', step=i): ...` — records a real
    begin/end timestamped event and a duration histogram sample."""
    return Span(name, **attrs)
