"""SLO engine: declarative objectives, multi-window burn rates,
breach-triggered capture.

Metrics alone answer "what is the p99 right now"; an operator needs
"are we burning the error budget fast enough to page, and what was the
process doing when we crossed the line". This module is the standard
SRE answer (multi-window multi-burn-rate alerting) wired into the
machinery this repo already has:

- **Objectives are declarative**: `Objective.latency_p99` judges a
  windowed-quantile gauge against a latency bound (TTFT p99 vs the
  serving SLO), `Objective.ratio` judges bad/total counter pairs
  (availability from `paddle_router_requests_total{outcome=...}`,
  shed rate from `paddle_router_shed_total`). Each carries an error
  BUDGET — the allowed bad fraction.
- **Evaluated over the aggregated fleet view** when a view function is
  given (the Aggregator's `merged()` doc — the same shape
  `merge_snapshots` produces), falling back to the local registry
  snapshot, so one engine definition works single-process and fleet.
- **Multi-window burn rates**: each `poll()` appends the tick's bad
  fraction to a short (default 5 m) and a long (default 1 h) sliding
  window; burn = mean bad fraction / budget. The alert fires only
  when BOTH windows exceed the burn threshold — the short window gives
  fast detection, the long window keeps a transient blip from paging —
  and clears when the short window recovers.
- **Breaches capture their own evidence**: the `slo_breach` event is a
  flight-recorder trigger (the bundle carries rings, metrics, traces,
  and this engine's burn state), and when a capture directory is
  configured the engine additionally starts a BOUNDED
  `jax.profiler.trace` (stopped by a timer — a breach must never
  leave an unbounded profiler running).

Gauges published per objective: `paddle_slo_error_budget_remaining`
(1.0 = untouched budget, 0.0 = fully burned over the long window),
`paddle_slo_burn_rate{slo,window}`, and `paddle_slo_alerting`.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

DEFAULT_SHORT_WINDOW_S = 300.0     # 5 m: fast detection
DEFAULT_LONG_WINDOW_S = 3600.0     # 1 h: sustained confirmation
DEFAULT_BURN_ALERT = 10.0          # page when burning 10x budget


def _view_samples(view: Dict[str, Any], name: str
                  ) -> List[Dict[str, Any]]:
    for m in view.get('metrics', []):
        if m['name'] == name:
            return list(m.get('samples', []))
    return []


def _sum_matching(view: Dict[str, Any], name: str,
                  match: Optional[Dict[str, str]] = None) -> float:
    total = 0.0
    for s in _view_samples(view, name):
        labels = s.get('labels', {})
        if match and not all(labels.get(k) == v
                             for k, v in match.items()):
            continue
        total += float(s.get('value', 0.0))
    return total


def _max_value(view: Dict[str, Any], name: str) -> Optional[float]:
    vals = [float(s.get('value', 0.0)) for s in _view_samples(view, name)]
    return max(vals) if vals else None


@dataclasses.dataclass
class Objective:
    """One declarative SLO. Use the constructors — `kind` selects how a
    tick's bad fraction is computed from the (fleet) view:

    - `latency_p99`: gauge `metric` (a windowed-quantile gauge; the
      fleet merge takes the worst process) against `threshold_s`; the
      tick is bad (1.0) while the quantile sits over the bound.
    - `ratio`: bad/total counter families with optional label matches;
      the tick's bad fraction is d(bad)/d(total) since the last poll
      (no traffic → no data → the tick is skipped, honestly).
    """

    name: str
    kind: str                       # 'latency_p99' | 'ratio'
    budget: float                   # allowed bad fraction, e.g. 0.001
    description: str = ''
    metric: str = ''                # latency_p99: the quantile gauge
    threshold_s: float = 0.0
    bad: Sequence[Tuple[str, Optional[Dict[str, str]]]] = ()
    total: Sequence[Tuple[str, Optional[Dict[str, str]]]] = ()

    def __post_init__(self):
        if not 0.0 < self.budget < 1.0:
            raise ValueError(f'budget must be in (0, 1); '
                             f'got {self.budget}')
        if self.kind not in ('latency_p99', 'ratio'):
            raise ValueError(f'unknown objective kind {self.kind!r}')

    @staticmethod
    def latency_p99(name: str, metric: str, threshold_s: float,
                    budget: float, description: str = '') -> 'Objective':
        return Objective(name=name, kind='latency_p99', budget=budget,
                         metric=metric, threshold_s=float(threshold_s),
                         description=description
                         or f'{metric} <= {threshold_s}s')

    @staticmethod
    def ratio(name: str, bad, total, budget: float,
              description: str = '') -> 'Objective':
        def norm(spec):
            out = []
            for item in (spec if isinstance(spec, (list, tuple))
                         and spec and isinstance(spec[0], (list, tuple))
                         else [spec]):
                if isinstance(item, str):
                    out.append((item, None))
                else:
                    nm, match = item
                    out.append((nm, dict(match) if match else None))
            return tuple(out)
        return Objective(name=name, kind='ratio', budget=budget,
                         bad=norm(bad), total=norm(total),
                         description=description or name)


def default_objectives(slo_ttft_s: float = 1.0) -> List[Objective]:
    """The serving objectives the ISSUE names: TTFT p99 against the
    latency SLO, availability (failed / routed), shed rate (shed /
    offered = routed + shed)."""
    routed = ('paddle_router_requests_total', None)
    shed = ('paddle_router_shed_total', None)
    return [
        Objective.latency_p99(
            'ttft_p99', 'paddle_ttft_p99_window', slo_ttft_s,
            budget=0.05,
            description=f'router TTFT p99 under {slo_ttft_s}s'),
        Objective.ratio(
            'availability',
            bad=('paddle_router_requests_total', {'outcome': 'failed'}),
            total=[routed], budget=0.01,
            description='routed requests that fail'),
        Objective.ratio(
            'shed_rate', bad=[shed], total=[routed, shed], budget=0.05,
            description='offered load rejected by admission control'),
    ]


class SLOEngine:
    """Evaluate objectives over sliding windows; alert on multi-window
    burn; capture on breach.

    Args:
        objectives: the declarative objective list.
        view_fn: zero-arg callable returning a merged metrics doc (an
            `Aggregator.merged()`; None → the local registry snapshot,
            which shares the shape).
        clock: injectable monotonic clock — windows and tests run on it.
        short_window_s / long_window_s / burn_alert: the multi-window
            burn-rate alert shape.
        capture_dir: when set, a breach starts a bounded
            `jax.profiler.trace` here for `capture_s` seconds.
        flight: emit `slo_breach` (a flight-recorder trigger) on alert
            transitions (off for engines running inside benches).
    """

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 view_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 short_window_s: float = DEFAULT_SHORT_WINDOW_S,
                 long_window_s: float = DEFAULT_LONG_WINDOW_S,
                 burn_alert: float = DEFAULT_BURN_ALERT,
                 capture_dir: Optional[str] = None,
                 capture_s: float = 3.0,
                 flight: bool = True):
        if long_window_s <= short_window_s:
            raise ValueError('long_window_s must exceed short_window_s')
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f'duplicate objective names in {names}')
        self._view_fn = view_fn
        self._clock = clock
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_alert = float(burn_alert)
        self.capture_dir = capture_dir
        self.capture_s = float(capture_s)
        self._flight = bool(flight)
        self._lock = _concurrency.Lock('SLOEngine._lock')
        self._windows: Dict[str, Tuple[Any, Any]] = {}
        for o in self.objectives:
            self._windows[o.name] = (
                _metrics.SlidingWindow(self.short_window_s, clock=clock),
                _metrics.SlidingWindow(self.long_window_s, clock=clock))
        self._counter_base: Dict[str, Tuple[float, float]] = {}
        self._alerting: Dict[str, bool] = {o.name: False
                                           for o in self.objectives}
        self._breaches: List[Dict[str, Any]] = []
        self._capturing = False
        reg = _metrics.get_registry()
        self._m_budget = reg.gauge(
            'paddle_slo_error_budget_remaining',
            'fraction of the error budget left over the long burn '
            'window (1 = untouched, 0 = fully burned)', ('slo',))
        self._m_burn = reg.gauge(
            'paddle_slo_burn_rate',
            'error-budget burn rate (bad fraction / budget) per '
            'window', ('slo', 'window'))
        self._m_alerting = reg.gauge(
            'paddle_slo_alerting',
            '1 while the multi-window burn alert is firing', ('slo',))
        self._m_breaches = reg.counter(
            'paddle_slo_breaches_total',
            'burn-rate alert transitions into firing', ('slo',))

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _view(self) -> Dict[str, Any]:
        if self._view_fn is not None:
            return self._view_fn()
        return _metrics.get_registry().snapshot()

    def _tick_bad_fraction(self, o: Objective,
                           view: Dict[str, Any]) -> Optional[float]:
        if o.kind == 'latency_p99':
            v = _max_value(view, o.metric)
            if v is None:
                return None
            return 1.0 if v > o.threshold_s else 0.0
        bad = sum(_sum_matching(view, nm, match) for nm, match in o.bad)
        total = sum(_sum_matching(view, nm, match)
                    for nm, match in o.total)
        base = self._counter_base.get(o.name)
        self._counter_base[o.name] = (bad, total)
        if base is None:
            return None    # first poll: no interval to judge yet
        d_bad, d_total = bad - base[0], total - base[1]
        if d_total <= 0:
            return None    # no traffic this tick: no evidence either way
        return min(max(d_bad / d_total, 0.0), 1.0)

    def poll(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation tick: read the view, update every objective's
        windows/gauges, fire or clear alerts. Returns `report()`."""
        del now   # windows read the injected clock directly
        view = self._view()
        fired: List[str] = []
        recovered: List[str] = []
        with self._lock:
            for o in self.objectives:
                frac = self._tick_bad_fraction(o, view)
                short, long_ = self._windows[o.name]
                if frac is not None:
                    short.observe(frac)
                    long_.observe(frac)
                burn_s = self._burn(short, o)
                burn_l = self._burn(long_, o)
                remaining = self._budget_remaining(long_, o)
                alert = (burn_s is not None and burn_l is not None
                         and burn_s >= self.burn_alert
                         and burn_l >= self.burn_alert)
                # latch: fire when BOTH windows burn hot, clear only
                # when the short (detection) window cools back down
                was = self._alerting[o.name]
                now_alerting = was
                if alert and not was:
                    now_alerting = True
                    fired.append(o.name)
                    self._breaches.append({
                        'slo': o.name, 'wall_ts': time.time(),
                        'burn_short': burn_s, 'burn_long': burn_l,
                        'budget_remaining': remaining})
                elif was and burn_s is not None \
                        and burn_s < self.burn_alert:
                    now_alerting = False
                    recovered.append(o.name)
                self._alerting[o.name] = now_alerting
                if _metrics.enabled():
                    if burn_s is not None:
                        self._m_burn.labels(
                            slo=o.name, window='short').set(burn_s)
                    if burn_l is not None:
                        self._m_burn.labels(
                            slo=o.name, window='long').set(burn_l)
                    if remaining is not None:
                        self._m_budget.labels(slo=o.name).set(remaining)
                    self._m_alerting.labels(slo=o.name).set(
                        1.0 if self._alerting[o.name] else 0.0)
        for name in fired:
            if _metrics.enabled():
                self._m_breaches.labels(slo=name).inc()
            if self._flight:
                from .events import emit
                last = self._breaches[-1]
                emit('slo_breach', slo=name,
                     burn_short=round(last['burn_short'], 3),
                     burn_long=round(last['burn_long'], 3),
                     budget_remaining=last['budget_remaining'])
            self._maybe_capture(name)
        for name in recovered:
            if self._flight:
                from .events import emit
                emit('slo_recovered', slo=name)
        return self.report()

    @staticmethod
    def _burn(window, o: Objective) -> Optional[float]:
        mean = window.mean()
        if mean is None:
            return None
        return mean / o.budget

    @staticmethod
    def _budget_remaining(long_window, o: Objective) -> Optional[float]:
        mean = long_window.mean()
        if mean is None:
            return None
        return max(0.0, min(1.0, 1.0 - mean / o.budget))

    # ------------------------------------------------------------------
    # breach capture
    # ------------------------------------------------------------------
    def _maybe_capture(self, slo_name: str):
        """Bounded jax.profiler capture on breach: start a device trace
        into `capture_dir` and stop it after `capture_s` via a timer.
        Best-effort — a missing/busy profiler must never make a breach
        worse."""
        if self.capture_dir is None or self.capture_s <= 0:
            return
        with self._lock:
            if self._capturing:
                return
            self._capturing = True
        try:
            import os
            import jax
            path = os.path.join(self.capture_dir,
                                f'slo_{slo_name}_{int(time.time())}')
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)

            def _stop():
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    _metrics.count_suppressed('slo.capture_stop')
                finally:
                    with self._lock:
                        self._capturing = False
            threading.Timer(self.capture_s, _stop).start()
            from .events import emit
            emit('slo_capture', slo=slo_name, path=path,
                 capture_s=self.capture_s)
        except Exception:
            # profiler unavailable (CPU-only wheel, capture already
            # running): the breach evidence is the flight bundle
            _metrics.count_suppressed('slo.capture')
            with self._lock:
                self._capturing = False

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def alerting(self, name: str) -> bool:
        with self._lock:
            return bool(self._alerting.get(name))

    def report(self) -> Dict[str, Any]:
        """The /slo payload (and the flight bundle's SLO section)."""
        out = []
        with self._lock:
            for o in self.objectives:
                short, long_ = self._windows[o.name]
                out.append({
                    'name': o.name, 'kind': o.kind,
                    'description': o.description,
                    'budget': o.budget,
                    'threshold_s': o.threshold_s or None,
                    'burn_short': self._burn(short, o),
                    'burn_long': self._burn(long_, o),
                    'budget_remaining': self._budget_remaining(long_, o),
                    'alerting': self._alerting[o.name],
                })
            breaches = list(self._breaches[-32:])
        return {'objectives': out, 'breaches': breaches,
                'burn_alert': self.burn_alert,
                'windows_s': [self.short_window_s, self.long_window_s]}


# ---------------------------------------------------------------------------
# process-wide registration (the /slo endpoint + flight bundle read this)
# ---------------------------------------------------------------------------

_engine: List[Optional[SLOEngine]] = [None]


def set_engine(engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    _engine[0] = engine
    return engine


def get_engine() -> Optional[SLOEngine]:
    return _engine[0]
