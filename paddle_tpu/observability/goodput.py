"""Goodput ledger: explain every wall-clock second of a run.

ROADMAP's MFU push is blocked on attribution — the runtime records
counters, spans, and per-program cost_analysis FLOPs, but nothing says
*where the seconds went* in a run that compiles, retries, re-meshes,
checkpoints, and serves. PaLM-style MFU accounting and MegaScale's
goodput diagnostics (arXiv:2402.15627) both start from the same
instrument: a ledger that classifies 100% of wall time into productive
vs. overhead categories, with the unexplained remainder reported as an
explicit residual — never hidden inside a category it doesn't belong
to.

The `GoodputLedger` is an `EventLog` listener: every span the runtime
already records (train steps, compiles, checkpoint save/restore, retry
backoff, rollback restores, elastic re-mesh, serving prefill/decode,
drain, data wait) is mapped by name into one of the taxonomy's
categories. Per-thread interval bookkeeping subtracts nested spans from
their parents, so a compile inside a train step counts once — as
compile — and the step keeps only its own surplus. Two events
re-classify after the fact:

- `bad_step`: the step that just computed a NaN/spike loss was *not*
  productive; its seconds move from `step_compute` to `rollback`
  (PaLM's "wasted step" accounting), joined by the restore span.

The invariant: `sum(categories) + residual == wall_seconds` exactly
(residual is computed as the difference and reported, including the
`overcount` case where concurrent threads attribute more busy seconds
than one wall clock holds). The bench `goodput` phase fault-injects a
retry, a rollback, and a checkpoint and asserts each lands in its
category and the books close within 1%.

Always on (installed at package import, like the flight recorder);
`stop()`/`start()` detach/reattach the listener for A/B measurement,
`reset()` opens a fresh measurement window. Ledger state mirrors into
`paddle_goodput_seconds_total{category}` / `paddle_goodput_fraction` /
`paddle_goodput_wall_seconds_total` at scrape time, and
`fleet_utils.gather_registry` sums seconds across hosts and recomputes
the fractions (observability.metrics._recompute_goodput_fractions).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from . import events as _events
from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

# the exhaustive, non-overlapping taxonomy (order = report order).
# 'residual' is computed, not accumulated: wall - sum(attributed).
CATEGORIES = (
    'step_compute',        # productive train-step device+host time
    'compile',             # jaxpr trace + XLA backend compile
    'checkpoint_save',
    'checkpoint_restore',
    'retry_backoff',       # transient-error backoff sleeps
    'rollback',            # wasted bad-step compute + snapshot restore
    'remesh',              # elastic shrink/grow transitions
    'preemption_drain',    # serving graceful-drain surplus
    'weight_swap',         # trainer→serving hot-swap (drain/load/
                           # verify/rejoin surplus; nested decode keeps
                           # serving while a replica drains)
    'scale_up',            # autoscaler replica provisioning (engine
                           # build + program-store warm load)
    'scale_down',          # autoscaler cordon/removal surplus (nested
                           # decode during the drain stays serving)
    'serving_prefill',
    'serving_decode',
    'host_wait',           # data-loader / input-pipeline wait
)

# span name -> category. Spans not listed here (profiler RecordEvent
# user regions, serving queue spans on requester threads) are ignored:
# their time stays in whatever enclosing category covers it, or in the
# residual — which is the honest answer for unclassified work.
SPAN_CATEGORIES: Dict[str, str] = {
    'train.step': 'step_compute',
    'fleet.dist_train_step': 'step_compute',
    'bench.eager_step': 'step_compute',
    'step.compute': 'step_compute',
    'jit.trace': 'compile',
    'jit.compile': 'compile',
    'checkpoint_save': 'checkpoint_save',
    'checkpoint_restore': 'checkpoint_restore',
    'resilience.backoff': 'retry_backoff',
    'resilience.rollback': 'rollback',
    'elastic.resize': 'remesh',
    'serving.drain': 'preemption_drain',
    # the rolling weight swap: sub-spans (drain wait, store load+verify,
    # health gate, rejoin) all book as weight_swap; decode rounds nested
    # inside the drain wait stay serving_decode — the fleet kept serving
    'hotswap.swap': 'weight_swap',
    'hotswap.drain': 'weight_swap',
    'hotswap.load': 'weight_swap',
    'hotswap.verify': 'weight_swap',
    'hotswap.rejoin': 'weight_swap',
    'hotswap.rollback': 'weight_swap',
    # autoscaling: provisioning books as scale_up; the cordon/removal
    # bookkeeping as scale_down — the drain itself is NOT wrapped, so
    # decode rounds finishing the victim's work stay serving_decode
    # (the fleet kept serving; only the machinery is overhead)
    'autoscale.provision': 'scale_up',
    'autoscale.retire': 'scale_down',
    'serving.prefill': 'serving_prefill',
    'serving.prefill_chunk': 'serving_prefill',
    'serving.draft_prefill': 'serving_prefill',
    'serving.decode_round': 'serving_decode',
    'serving.spec_round': 'serving_decode',
    'step.data_wait': 'host_wait',
    'step.host_wait': 'host_wait',
}

# per-thread attributed-interval lists are pruned to this many entries;
# a parent span arriving after its children were pruned would double
# count, but parents always arrive within one span depth of their
# children so the horizon only needs to cover one step's fan-out
_MAX_INTERVALS = 256


class GoodputLedger:
    """Classifies wall time from the span stream; see module docstring.

    Thread model: `on_event` is called by EventLog.append from whatever
    thread ended the span; all state mutates under one lock. Per-thread
    interval lists make the nested-span subtraction exact for the
    strictly-nested spans one thread produces; across threads, busy
    seconds can legitimately exceed one wall clock (a serving engine
    decoding while the trainer steps) — that surplus is reported as
    `overcount_seconds`, never silently clipped.
    """

    def __init__(self, log: Optional[_events.EventLog] = None,
                 span_map: Optional[Dict[str, str]] = None):
        # `is None`, not truthiness: an empty EventLog is falsy
        self._log = _events.get_event_log() if log is None else log
        self._map = dict(span_map or SPAN_CATEGORIES)
        self._lock = _concurrency.Lock('GoodputLedger._lock')
        self._seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._intervals: Dict[int, List[Tuple[float, float]]] = {}
        # tid -> seconds the most recent step-span attributed (the
        # bad_step reclassification target)
        self._last_step: Dict[int, float] = {}
        self._t0 = _events._now()
        # per-program invocation counts at window start: the MFU
        # baseline (cost.record_roofline / aggregate_mfu divide the
        # window's executed FLOPs by the window's WALL seconds)
        self._mfu_baseline: Dict[str, int] = {}
        self._running = False

    # -- lifecycle -----------------------------------------------------------
    def start(self, reset: bool = False) -> 'GoodputLedger':
        """Attach to the event log (idempotent); `reset=True` also opens
        a fresh measurement window."""
        if reset:
            self.reset()
        if not self._running:
            self._running = True
            self._log.add_listener(self.on_event)
        return self

    def stop(self) -> 'GoodputLedger':
        """Detach from the event log; accumulated seconds survive (the
        A/B bench arms toggle this)."""
        self._running = False
        self._log.remove_listener(self.on_event)
        return self

    @property
    def running(self) -> bool:
        return self._running

    def reset(self):
        """Open a fresh window: zero every category, forget intervals,
        restart the wall clock at now, and re-baseline the MFU window
        (per-program invocation counts as of now)."""
        try:
            from .cost import get_catalog
            baseline = {r.name: r.invocations
                        for r in get_catalog().records()}
        except Exception:  # paddle-lint: disable=swallowed-exception -- catalog optional at reset; empty baseline just disables per-program MFU deltas
            baseline = {}
        with self._lock:
            self._seconds = {c: 0.0 for c in CATEGORIES}
            self._intervals.clear()
            self._last_step.clear()
            self._t0 = _events._now()
            self._mfu_baseline = baseline

    def mfu_window(self) -> 'Tuple[float, Dict[str, int]]':
        """(wall seconds since the last reset, invocation baseline at
        that reset) — the window cost.py's MFU/roofline math divides
        through."""
        with self._lock:
            return (max(_events._now() - self._t0, 0.0),
                    dict(self._mfu_baseline))

    # -- attribution ---------------------------------------------------------
    def on_event(self, event: Dict[str, Any]):
        name = event.get('name')
        if event.get('ph') == 'X':
            cat = self._map.get(name)
            if cat is None:
                return
            self._attribute(event.get('tid', 0), float(event['ts']),
                            float(event.get('dur', 0.0)), cat,
                            depth=event.get('depth'))
        elif name == 'bad_step':
            self._reclassify_last_step(event.get('tid', 0), 'rollback')

    def note_span(self, name: str, ts: float, dur: float,
                  tid: Optional[int] = None):
        """Direct-feed path for span-shaped regions that never touch the
        event log (jax.monitoring compile/trace durations — a busy
        dispatch cache compiles thousands of entries per session and
        would flush the bounded ring)."""
        if not self._running:
            return
        cat = self._map.get(name)
        if cat is None:
            return
        self._attribute(threading.get_ident() if tid is None else tid,
                        float(ts), float(dur), cat)

    def _attribute(self, tid: int, ts: float, dur: float,
                   cat: str, depth: Optional[int] = None) -> float:
        end = ts + dur
        with self._lock:
            if end <= self._t0:
                return 0.0   # span entirely before this window
            ts = max(ts, self._t0)    # clip spans straddling a reset
            dur = end - ts            # credit only the in-window part
            ivs = self._intervals.setdefault(tid, [])
            # children end (and arrive) before their parents, so any
            # already-attributed overlap on this thread is nested work
            # that must NOT count again under the parent's category
            overlap = 0.0
            kept = []
            for s, e in ivs:
                if e > ts and s < end:
                    overlap += min(e, end) - max(s, ts)
                    ts_u, end_u = min(ts, s), max(end, e)
                    ts, end = ts_u, end_u   # grow the union in place
                else:
                    kept.append((s, e))
            if depth == 1:
                # a TOP-LEVEL span just closed on this thread: no open
                # ancestor exists, so nothing recorded so far (this span
                # included) can overlap any later span — drop the
                # bookkeeping outright. Steady-state cost is O(1); the
                # capped scan only pays inside deep nesting.
                kept = []
            else:
                kept.append((ts, end))
                kept.sort()
                if len(kept) > _MAX_INTERVALS:
                    kept = kept[-_MAX_INTERVALS:]
            self._intervals[tid] = kept
            credit = max(dur - overlap, 0.0)
            self._seconds[cat] += credit
            if cat == 'step_compute':
                # remembered so bad_step can take this step's time back
                self._last_step[tid] = credit
            return credit

    def _reclassify_last_step(self, tid: int, to_cat: str):
        """A bad step's compute was waste, not goodput: move the most
        recent step-span credit on this thread into `to_cat`."""
        with self._lock:
            moved = self._last_step.pop(tid, 0.0)
            if moved > 0:
                self._seconds['step_compute'] -= moved
                self._seconds[to_cat] += moved

    # -- the books -----------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """Close the books on the current window.

        categories + residual always sum to wall_seconds exactly;
        `overcount_seconds` carries any cross-thread surplus (busy
        seconds beyond one wall clock) that was clipped OUT of the
        residual so fractions stay in [0, 1]."""
        now = _events._now()
        with self._lock:
            wall = max(now - self._t0, 0.0)
            cats = dict(self._seconds)
        attributed = sum(cats.values())
        residual = wall - attributed
        overcount = max(-residual, 0.0)
        residual = max(residual, 0.0)
        # normalize by the larger of wall and attributed: when
        # concurrent threads attribute more busy seconds than one wall
        # clock holds, fractions are shares of total accounted time and
        # still sum to 1 (the surplus itself rides overcount_seconds)
        denom = max(wall, attributed) or 1.0
        fractions = {c: v / denom for c, v in cats.items()}
        fractions['residual'] = residual / denom
        return {
            'running': self._running,
            'wall_seconds': wall,
            'categories': cats,
            'attributed_seconds': attributed,
            'residual_seconds': residual,
            'overcount_seconds': overcount,
            'fractions': fractions,
        }

    def report_text(self, max_width: int = 40) -> str:
        """Human-readable ledger table (examples print this at exit)."""
        r = self.report()
        lines = [f'goodput ledger: {r["wall_seconds"]:.3f} s wall '
                 f'({"running" if r["running"] else "stopped"})',
                 f'  {"category":<20}{"seconds":>10}{"fraction":>10}']
        rows = list(r['categories'].items()) \
            + [('residual', r['residual_seconds'])]
        for cat, secs in rows:
            frac = r['fractions'][cat]
            bar = '#' * int(round(frac * 20))
            lines.append(f'  {cat:<20}{secs:>10.3f}{frac:>10.1%}  {bar}')
        if r['overcount_seconds'] > 0:
            lines.append(f'  (+{r["overcount_seconds"]:.3f} s busy beyond '
                         f'one wall clock: concurrent threads)')
        return '\n'.join(lines)


_ledger = GoodputLedger()


def get_ledger() -> GoodputLedger:
    return _ledger


def _goodput_collector(reg: '_metrics.MetricsRegistry'):
    """Scrape-time mirror of the default ledger (mirror, not accumulate
    — the same contract every other collector follows). Residual rides
    the category label so `sum(paddle_goodput_seconds_total)` IS the
    wall clock; fractions are gauges the fleet merge recomputes."""
    r = _ledger.report()
    secs = reg.counter('paddle_goodput_seconds_total',
                       'wall seconds attributed per goodput category',
                       ('category',))
    frac = reg.gauge('paddle_goodput_fraction',
                     'fraction of wall time per goodput category',
                     ('category',))
    wall = reg.counter('paddle_goodput_wall_seconds_total',
                       'wall seconds covered by the goodput ledger '
                       'window')
    over = reg.gauge('paddle_goodput_overcount_seconds',
                     'attributed busy seconds beyond one wall clock '
                     '(concurrent threads)')
    rows = list(r['categories'].items()) \
        + [('residual', r['residual_seconds'])]
    for cat, v in rows:
        secs.labels(category=cat).value = max(float(v), 0.0)   # mirror
        frac.labels(category=cat).set(r['fractions'][cat])
    wall._sole().value = float(r['wall_seconds'])              # mirror
    over.set(r['overcount_seconds'])


def install():
    """Idempotent: start the always-on default ledger and register its
    scrape-time collector (runs at package import)."""
    _metrics.get_registry().register_collector(_goodput_collector)
    _ledger.start()
