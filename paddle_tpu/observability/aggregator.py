"""Aggregator: tail spool directories into one fleet view.

The pull half of the fleet observability plane: `poll()` scans a spool
directory for committed wire segments (`wire.py` format), verifies each
payload against its sha256 manifest, dedupes by `(process_uid, seq)` so
re-shipped segments are idempotent, and folds metric deltas into
per-process accumulation states. The merged fleet view applies the SAME
rules `fleet_utils.gather_registry` uses in-process (counters sum,
gauges max, goodput fractions recomputed) — `wire.merge_states`
delegates to `metrics.merge_snapshots`, one rule set for both planes.

Beyond metrics, the aggregator is the fleet's trace stitcher (Dapper):
span segments from router, scheduler, prefill, and decode processes
carry the existing `trace_id` (`RequestHandle.request_id`) in their
attrs; every segment header's `(wall_ts, mono_ts)` pair — sampled at
one instant on the shipping process — yields a per-process clock-skew
estimate (median of wall−mono), and `stitch_trace()` projects every
process's span clock onto the common wall timeline and renders one
chrome-trace waterfall with one labeled track per process.

A segment that fails decode (torn write, bit rot, version drift) is
QUARANTINED — renamed aside with its `.quarantined` suffix, counted,
evented — never applied, never crashed on: the WeightStore's
bad-payload posture applied to telemetry.
"""
from __future__ import annotations

import collections
import os
import statistics
import time
from typing import Any, Callable, Dict, List, Optional, Set

from . import metrics as _metrics
from . import wire
from ..analysis.runtime import concurrency as _concurrency

#: per-process bound on retained events/spans (oldest dropped) — the
#: aggregator is a view, not an archive
MAX_EVENTS_PER_PROCESS = 65536
#: per-process bound on retained request-ledger waterfalls
MAX_REQUESTS_PER_PROCESS = 4096
#: clock-pair samples retained per process for the skew estimate
MAX_CLOCK_PAIRS = 64


class Aggregator:
    """Tails one spool dir into per-process states + a merged view.

    Args:
        spool_dir: the directory shippers commit segments into.
        delete_applied: unlink a segment file once applied (spool
            retention for long-lived fleets). Off by default: with the
            files kept, a restarted aggregator rebuilds the identical
            merged view by re-applying everything once.
    """

    def __init__(self, spool_dir: str, delete_applied: bool = False):
        self.spool_dir = spool_dir
        self.delete_applied = bool(delete_applied)
        self._lock = _concurrency.Lock('Aggregator._lock')
        self._seen_paths: Set[str] = set()
        self._applied: Dict[str, Set[int]] = {}
        self._states: Dict[str, Dict[str, Any]] = {}
        self._events: Dict[str, collections.deque] = {}
        # finalized request-ledger waterfalls, per shipping process
        self._requests: Dict[str, collections.deque] = {}
        self._clock_pairs: Dict[str, collections.deque] = {}
        self._last_segment_wall: Dict[str, float] = {}
        self._quarantined: List[str] = []
        self._duplicates = 0
        self._applied_total = 0
        reg = _metrics.get_registry()
        self._m_applied = reg.counter(
            'paddle_segments_applied_total',
            'spool segments decoded, verified, and folded into the '
            'fleet view', ('kind',))
        self._m_duplicate = reg.counter(
            'paddle_segments_duplicate_total',
            're-shipped segments skipped by (process_uid, seq) dedupe')
        self._m_quarantined = reg.counter(
            'paddle_segments_quarantined_total',
            'spool segments that failed decode/sha256 and were moved '
            'aside unapplied')
        self._m_processes = reg.gauge(
            'paddle_fleet_processes',
            'distinct processes observed in the fleet spool')

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------
    def poll(self) -> Dict[str, int]:
        """One ingest pass over the spool; returns counts for this
        pass. Never raises on segment content — bad files quarantine."""
        applied = duplicates = quarantined = 0
        for path in self._segment_paths():
            if path in self._seen_paths:
                continue   # already decoded + applied on a prior poll
            outcome = self._ingest(path)
            self._seen_paths.add(path)
            if outcome == 'applied':
                applied += 1
            elif outcome == 'duplicate':
                duplicates += 1
            elif outcome == 'quarantined':
                quarantined += 1
        if _metrics.enabled():
            self._m_processes.set(len(self.process_uids()))
        return {'applied': applied, 'duplicates': duplicates,
                'quarantined': quarantined}

    def _segment_paths(self) -> List[str]:
        out = []
        try:
            subdirs = sorted(os.listdir(self.spool_dir))
        except OSError:
            return []   # spool not created yet: nothing shipped
        for sub in subdirs:
            d = os.path.join(self.spool_dir, sub)
            if not os.path.isdir(d):
                continue
            try:
                names = sorted(os.listdir(d))
            except OSError:
                continue   # raced with a cleanup; next poll rescans
            for name in names:
                if name.endswith(wire.SEGMENT_SUFFIX):
                    out.append(os.path.join(d, name))
        return out

    def _ingest(self, path: str) -> str:
        try:
            seg = wire.read_segment(path)
        except (wire.WireError, OSError, UnicodeDecodeError) as e:
            return self._quarantine(path, e)
        uid, seq = seg['process_uid'], int(seg['seq'])
        with self._lock:
            seen = self._applied.setdefault(uid, set())
            if seq in seen:
                self._duplicates += 1
                dup = True
            else:
                seen.add(seq)
                self._apply_locked(seg)
                self._applied_total += 1
                dup = False
        if dup:
            if _metrics.enabled():
                self._m_duplicate.inc()
            self._remove_applied(path)
            return 'duplicate'
        if _metrics.enabled():
            self._m_applied.labels(kind=seg['kind']).inc()
        self._remove_applied(path)
        return 'applied'

    def _remove_applied(self, path: str):
        if not self.delete_applied:
            return
        try:
            os.unlink(path)
        except OSError:
            pass   # a concurrent aggregator won the unlink; harmless

    def _quarantine(self, path: str, err: Exception) -> str:
        """Move a bad segment aside (atomic rename) so no later poll
        re-trips on it; the file survives for forensics."""
        qpath = path + wire.QUARANTINE_SUFFIX
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = path   # couldn't move it; remember it as-is
        with self._lock:
            self._quarantined.append(qpath)
        from .events import emit
        emit('segment_quarantined', path=os.path.basename(path),
             error=f'{type(err).__name__}: {err}')
        if _metrics.enabled():
            self._m_quarantined.inc()
        return 'quarantined'

    def _apply_locked(self, seg: Dict[str, Any]):
        uid, seq = seg['process_uid'], int(seg['seq'])
        pairs = self._clock_pairs.setdefault(
            uid, collections.deque(maxlen=MAX_CLOCK_PAIRS))
        pairs.append((float(seg['wall_ts']), float(seg['mono_ts'])))
        self._last_segment_wall[uid] = float(seg['wall_ts'])
        if seg['kind'] == wire.KIND_METRICS:
            state = self._states.get(uid)
            if state is None:
                state = self._states[uid] = wire.new_state(
                    uid, process_index=len(self._states))
            wire.fold_metrics_delta(state, seg['records'], seq)
        elif seg['kind'] == wire.KIND_REQUESTS:
            buf = self._requests.setdefault(
                uid, collections.deque(maxlen=MAX_REQUESTS_PER_PROCESS))
            buf.extend(seg['records'])
        else:   # events / spans share the per-process timeline buffer
            buf = self._events.setdefault(
                uid, collections.deque(maxlen=MAX_EVENTS_PER_PROCESS))
            buf.extend(seg['records'])

    # ------------------------------------------------------------------
    # the merged view
    # ------------------------------------------------------------------
    def merged(self) -> Dict[str, Any]:
        """Fleet-merged metrics doc (`merge_snapshots` shape): counters
        summed, gauges maxed across processes, goodput fractions
        recomputed."""
        with self._lock:
            # render under the lock: a concurrent poll() folding deltas
            # into a state mid-render would tear the snapshot
            snaps = [wire.state_to_snapshot(s)
                     for s in self._states.values()]
        return _metrics.merge_snapshots(snaps)

    def process_uids(self) -> List[str]:
        with self._lock:
            keys = (set(self._states) | set(self._events)
                    | set(self._requests))
            return sorted(keys)

    def requests(self, trace_id=None) -> List[Dict[str, Any]]:
        """Fleet-merged finalized request-ledger waterfalls (oldest
        first by finish wall time), each tagged with the process that
        shipped it. `trace_id` filters to one request's record(s) — the
        `/requests` → `/fleet/trace` drill-down."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for uid, buf in self._requests.items():
                for r in buf:
                    if (trace_id is not None
                            and r.get('request_id') != trace_id):
                        continue
                    rr = dict(r)
                    rr['process_uid'] = uid
                    out.append(rr)
        out.sort(key=lambda r: r.get('wall_ts') or 0.0)
        return out

    def per_process_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Each process's accumulated metrics as a snapshot-shaped doc,
        keyed by process_uid — the per-process half of /fleet/metrics."""
        with self._lock:
            return {uid: wire.state_to_snapshot(s)
                    for uid, s in self._states.items()}

    def per_process_value(self, name: str, default: float = 0.0,
                          agg: str = 'sum', **labels) -> Dict[str, float]:
        """One metric's current value per process — counters/gauges.
        With labels given, only matching samples count; `agg` folds a
        labeled family's samples within one process ('sum' or 'max')."""
        out: Dict[str, float] = {}
        with self._lock:
            for uid, state in self._states.items():
                fam = state['families'].get(name)
                if fam is None:
                    out[uid] = default
                    continue
                vals = [s['value'] for s in fam['samples'].values()
                        if 'value' in s   # counters/gauges only
                        and all(s['labels'].get(k) == str(v)
                                for k, v in labels.items())]
                if not vals:
                    out[uid] = default
                elif agg == 'max':
                    out[uid] = max(vals)
                else:
                    out[uid] = sum(vals)
        return out

    def segment_ages(self, now: Optional[float] = None
                     ) -> Dict[str, float]:
        """Seconds since each process's newest segment (wall clock) —
        the freshness signal consumers use to ignore dead shippers."""
        now = time.time() if now is None else now
        with self._lock:
            return {uid: now - w
                    for uid, w in self._last_segment_wall.items()}

    def events_dropped(self) -> Dict[str, float]:
        """Per-process event-ring drop counts from the shipped
        `paddle_events_dropped_total` mirror — the fleet answer to
        'whose traces are truncated'."""
        return self.per_process_value('paddle_events_dropped_total')

    # ------------------------------------------------------------------
    # clock skew + trace stitching
    # ------------------------------------------------------------------
    def clock_offsets(self) -> Dict[str, float]:
        """Per-process offset mapping that process's span clock onto
        its wall clock: median over shipped (wall_ts − mono_ts) pairs.
        Robust to a slow ship (both stamps taken at one instant, so
        shipping latency cancels); NTP-disciplined wall clocks are the
        cross-process common reference, per Dapper's model."""
        with self._lock:
            return {uid: statistics.median(w - m for w, m in pairs)
                    for uid, pairs in self._clock_pairs.items() if pairs}

    def trace_ids(self) -> List[int]:
        """Distinct request trace ids observed across all processes."""
        ids = set()
        with self._lock:
            for buf in self._events.values():
                for e in buf:
                    rid = (e.get('attrs') or {}).get('request_id')
                    if rid is not None:
                        ids.add(rid)
        return sorted(ids)

    def stitch_trace(self, trace_id=None,
                     path: Optional[str] = None) -> Dict[str, Any]:
        """Chrome-trace waterfall across processes, one labeled track
        (pid) per process, timestamps skew-corrected onto the common
        wall timeline and rebased to the earliest event. `trace_id`
        restricts to spans/events whose attrs carry that `request_id`
        (the cross-process request waterfall); None stitches
        everything."""
        from .exporters import chrome_track_metadata
        offsets = self.clock_offsets()
        with self._lock:
            per_proc = {uid: list(buf)
                        for uid, buf in self._events.items()}
            per_proc_reqs = {uid: list(buf)
                             for uid, buf in self._requests.items()}
        rows: List[Dict[str, Any]] = []     # (corrected wall ts, event)
        tracks: List[Dict[str, Any]] = []
        t_min: Optional[float] = None
        all_uids = sorted(set(per_proc) | set(per_proc_reqs))
        for pid, uid in enumerate(all_uids):
            off = offsets.get(uid, 0.0)
            tids: Set[int] = set()
            kept = []
            for e in per_proc.get(uid, ()):
                if trace_id is not None and (
                        (e.get('attrs') or {}).get('request_id')
                        != trace_id):
                    continue
                wall = float(e.get('ts', 0.0)) + off
                kept.append((wall, e))
                tids.add(e.get('tid', 0))
                if t_min is None or wall < t_min:
                    t_min = wall
            # request-ledger phase annotations: each finalized record's
            # waterfall renders as `req.<phase>` slices on a synthetic
            # per-request track, skew-corrected exactly like spans (the
            # record's 'ts' rides the span clock)
            for r in per_proc_reqs.get(uid, ()):
                rid = r.get('request_id')
                if trace_id is not None and rid != trace_id:
                    continue
                base = float(r.get('ts', 0.0))
                tid = -1 - (int(rid or 0) % 97)
                for s in r.get('segments', ()):
                    e = {'name': f'req.{s["phase"]}', 'ph': 'X',
                         'ts': base + float(s['start_s']),
                         'dur': float(s['dur_s']), 'tid': tid,
                         'attrs': {'request_id': rid,
                                   'phase': s['phase'],
                                   'outcome': r.get('outcome'),
                                   'failovers': r.get('failovers')}}
                    wall = e['ts'] + off
                    kept.append((wall, e))
                    tids.add(tid)
                    if t_min is None or wall < t_min:
                        t_min = wall
            rows.extend((wall, pid, e) for wall, e in kept)
            if kept:
                tracks.append({'pid': pid, 'uid': uid, 'tids': tids,
                               'offset': off})
        t0 = t_min if t_min is not None else 0.0
        trace_events: List[Dict[str, Any]] = []
        for tr in tracks:
            trace_events.extend(chrome_track_metadata(
                tr['pid'], f'process {tr["uid"]}',
                {tid: f'tid {tid}' for tid in sorted(tr['tids'])},
                sort_index=tr['pid']))
        for wall, pid, e in sorted(rows, key=lambda r: r[0]):
            out = {'name': e['name'], 'ph': e.get('ph', 'X'), 'pid': pid,
                   'tid': e.get('tid', 0),
                   'ts': int((wall - t0) * 1e6)}
            if out['ph'] == 'X':
                out['dur'] = int(e.get('dur', 0.0) * 1e6)
            if out['ph'] == 'i':
                out['s'] = 't'
            args = dict(e.get('attrs') or {})
            if 'depth' in e:
                args['depth'] = e['depth']
            if args:
                out['args'] = args
            trace_events.append(out)
        doc = {'traceEvents': trace_events, 'displayTimeUnit': 'ms',
               'metadata': {'trace_id': trace_id,
                            'clock_offsets': {t['uid']: t['offset']
                                              for t in tracks},
                            'wall_t0': t0}}
        if path is not None:
            import json
            with open(path, 'w') as f:
                json.dump(doc, f)
        return doc

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'spool_dir': self.spool_dir,
                'processes': sorted(set(self._states)
                                    | set(self._events)),
                'segments_applied': self._applied_total,
                'duplicates_skipped': self._duplicates,
                'quarantined': list(self._quarantined),
                'last_segment_wall_ts': dict(self._last_segment_wall),
            }


class FleetSignalSource:
    """`Router.window_signals()`-shaped control signals from the FLEET
    view instead of the local registry — the autoscaler's eyes once
    replicas live in other processes.

    Reads the per-process windowed signal gauges the routers already
    export (`paddle_ttft_p99_window`, `paddle_queue_depth_p99_window`,
    `paddle_shed_rate_window`, `paddle_router_available_replicas`) from
    the aggregator's states, then folds them the way the quantity
    means: latency quantiles take the fleet-wide WORST (max — the SLO
    is judged at the slowest router), queue depth / shed rate /
    serving replicas SUM (capacity and demand add across processes).
    Falls back to `router.window_signals()` while the spool has no
    fresh data (fleet plane warming up, or a single-process
    deployment), so wiring it in is never a regression.

    Args:
        aggregator: the fleet Aggregator to read.
        router: optional local Router for the warm-up fallback.
        fresh_s: ignore the fleet view when its newest segment is older
            than this (a dead shipper must not freeze the autoscaler
            on stale signals).
        poll: run `aggregator.poll()` on every read (default True —
            the autoscaler's cadence is slow enough to pay an ingest).
    """

    def __init__(self, aggregator: Aggregator, router=None,
                 fresh_s: float = 30.0, poll: bool = True,
                 clock: Callable[[], float] = time.time):
        self.aggregator = aggregator
        self.router = router
        self.fresh_s = float(fresh_s)
        self._poll = bool(poll)
        self._clock = clock

    def _fresh_uids(self) -> List[str]:
        ages = self.aggregator.segment_ages(self._clock())
        return [uid for uid, age in ages.items() if age <= self.fresh_s]

    def __call__(self) -> Dict[str, Any]:
        if self._poll:
            self.aggregator.poll()
        fresh = set(self._fresh_uids())
        agg = self.aggregator
        ttft = {u: v for u, v in agg.per_process_value(
            'paddle_ttft_p99_window', default=-1.0, agg='max').items()
            if u in fresh and v >= 0.0}
        queue = {u: v for u, v in agg.per_process_value(
            'paddle_queue_depth_p99_window', default=-1.0,
            agg='max').items() if u in fresh and v >= 0.0}
        shed = {u: v for u, v in agg.per_process_value(
            'paddle_shed_rate_window').items() if u in fresh}
        serving = {u: v for u, v in agg.per_process_value(
            'paddle_router_available_replicas').items() if u in fresh}
        if not serving and not ttft and not queue:
            # fleet plane dark. Two very different darknesses: a spool
            # that has never shipped (warm-up / single-process — quiet
            # fallback) vs a spool with data that has ALL gone stale (a
            # dead shipper fleet-wide) — the latter is an incident, so
            # it counts and emits instead of degrading silently.
            ages = self.aggregator.segment_ages(self._clock())
            if ages and not fresh:
                from .events import emit
                if _metrics.enabled():
                    _metrics.get_registry().counter(
                        'paddle_fleet_signals_stale_total',
                        'FleetSignalSource reads that fell back to the '
                        'local router because every per-process signal '
                        'was stale').inc()
                emit('fleet_signals_stale',
                     processes=len(ages),
                     oldest_age_s=round(max(ages.values()), 3),
                     fresh_s=self.fresh_s)
            if self.router is not None:
                sig = dict(self.router.window_signals())
                sig['source'] = 'local'
                return sig
            return {'ttft_p99': None, 'queue_p99': None, 'shed_rate': 0.0,
                    'serving_replicas': 0, 'source': 'fleet_empty'}
        return {
            'ttft_p99': max(ttft.values()) if ttft else None,
            'queue_p99': sum(queue.values()) if queue else None,
            'shed_rate': sum(shed.values()),
            'serving_replicas': int(sum(serving.values())),
            'processes': sorted(fresh),
            'source': 'fleet',
        }


# ---------------------------------------------------------------------------
# process-wide registration (the /fleet/* endpoints read this)
# ---------------------------------------------------------------------------

_aggregator: List[Optional[Aggregator]] = [None]


def set_aggregator(agg: Optional[Aggregator]) -> Optional[Aggregator]:
    """Register the process's fleet aggregator; the observability
    server's `/fleet/metrics` and `/fleet/trace` serve from it."""
    _aggregator[0] = agg
    return agg


def get_aggregator() -> Optional[Aggregator]:
    return _aggregator[0]
