"""Per-request latency ledger: explain every millisecond of the p99.

The goodput ledger (goodput.py) explains every wall-clock second of the
FLEET; this module applies the same closure discipline to ONE request.
Dean & Barroso ("The Tail at Scale") and Dapper both argue the tail is
only debuggable with per-request, cross-component attribution — when
the TTFT p99 breaches, "where did my p99 go" needs an answer naming a
phase, not a histogram.

Every request's lifetime decomposes into an exhaustive, non-overlapping
taxonomy (`PHASES`), with the unexplained remainder reported as an
explicit residual — never hidden inside a phase it doesn't belong to:

  admission          tenancy/QoS checks + replica pick + seating work
  queue_wait         submitted but not seated; partitioned by the
                     BLOCKING REASON sampled at each scheduler pass
                     (`BLOCKED_REASONS`)
  prefix_lookup      radix prefix-cache probe at seating
  prefill            this request's own prefill compute (whole-prompt
                     or per chunk/bucket; draft-model prefill included)
  prefill_wait       seated while ANOTHER slot's prefill chunk runs —
                     the chunked-prefill convoy, named explicitly
  decode             batched decode rounds. Waterfall book: each
                     participant is charged the FULL round wall (the
                     request really waited that long), so per-request
                     phases sum to E2E. Fair-share book:
                     `decode_fair_s` = round_wall / n_active per round,
                     so per-request fair shares sum to the ENGINE
                     decode wall — both closures are tier-1-asserted.
  spec_verify        speculation rounds (draft + target verify),
                     rejected-draft cost included
  rpc_transport      framed-RPC surplus on process replicas (parent
                     round wall minus the child's reported step wall)
  failover_resubmit  replica-death detection + re-placement gap
  retry_backoff      transient-retry backoff sleeps attributable to
                     this request (reserved: today's per-call retries
                     ride inside the round phase that ran them)

Records attach to request handles (`handle._ledger_rec`) and are
mutated only by the thread driving that handle (the engine/router
loop); the ledger itself only aggregates FINALIZED records, under its
lock. One record survives failover: the router re-points the fresh
engine handle at the original record, so the waterfall spans replicas.

Tail exemplars keep full waterfalls for the slowest K per sliding
window plus a reservoir sample of everything else; `report()` is the
`/requests` payload (per-phase p50/p99 decomposition, a "p99 driver"
ranking = which phase dominates at the tail, blocked-reason ranking).
Finalized records also ride the PR-17 wire plane as a dedicated
segment kind (`wire.KIND_REQUESTS`) so the Aggregator merges fleets
and `stitch_trace` gains per-phase annotations.
"""
from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional, Sequence

from . import events as _events
from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

# the exhaustive, non-overlapping per-request taxonomy (report order).
# 'residual' is computed at finalize, never accumulated.
PHASES = (
    'admission',
    'queue_wait',
    'prefix_lookup',
    'prefill',
    'prefill_wait',
    'decode',
    'spec_verify',
    'rpc_transport',
    'failover_resubmit',
    'retry_backoff',
)

# queue_wait partition: the blocking reason sampled at each scheduler
# pass / requeue. The vocabulary is closed — dashboards group by it.
BLOCKED_REASONS = (
    'pool_exhausted',       # KV page/slot reservation failed; requeued
    'adapter_pinned',       # adapter bank full of pinned slots; requeued
    'priority_queued',      # waiting behind other admissible work
    'breaker_open',         # origin replica circuit-broken; waiting on
                            # a survivor's queue after failover
    'no_healthy_replica',   # no failover target existed at sample time
)

#: per-record waterfall segment cap — beyond it, phase seconds still
#: accumulate (closure holds) but the rendered waterfall truncates
MAX_SEGMENTS = 256
#: adjacent same-phase segments closer than this coalesce
_COALESCE_GAP_S = 1e-4


class RequestRecord:
    """One request's phase books. Mutated by the driving thread only;
    handed to the ledger exactly once, at finalize."""

    __slots__ = (
        'request_id', 'tenant', 'priority', 'adapter_id', 't_submit',
        't_first', 't_done', 'outcome', 'tokens', 'failovers',
        'replica_id', 'phases', 'ttft_phases', 'blocked', 'decode_fair_s',
        'segments', 'segments_dropped', 'wall_ts',
        '_q_mark', '_q_reason', '_last_touch', '_owner',
    )

    def __init__(self, request_id: int, t_submit: float,
                 tenant: Optional[str] = None,
                 priority: Optional[int] = None,
                 adapter_id: Optional[str] = None):
        self.request_id = request_id
        self.tenant = tenant
        self.priority = priority
        self.adapter_id = adapter_id
        self.t_submit = float(t_submit)
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.outcome: Optional[str] = None
        self.tokens = 0
        self.failovers = 0
        self.replica_id: Optional[int] = None
        self.phases: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
        # the TTFT sub-book: phase seconds accrued while no token had
        # been emitted yet — closes against measured TTFT
        self.ttft_phases: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
        self.blocked: Dict[str, float] = {}
        self.decode_fair_s = 0.0
        self.segments: List[List[float]] = []   # [phase_idx, start, dur]
        self.segments_dropped = 0
        self.wall_ts: Optional[float] = None
        self._q_mark: Optional[float] = None
        self._q_reason = 'priority_queued'
        self._last_touch = self.t_submit
        # the ledger this record finalizes into (set by open(); handle
        # hooks route through it so a bench/test ledger keeps its own
        # books instead of leaking into the default singleton's)
        self._owner: Optional['RequestLedger'] = None

    # -- phase attribution -------------------------------------------------
    def add(self, phase: str, dur: float, now: Optional[float] = None):
        """Attribute `dur` seconds ending at `now` to `phase` (both
        books the phase belongs to: waterfall always; TTFT sub-book
        while the first token is still pending)."""
        if dur <= 0.0:
            return
        end = time.perf_counter() if now is None else now
        self.phases[phase] += dur
        if self.t_first is None:
            self.ttft_phases[phase] += dur
        self._last_touch = end
        start = end - dur - self.t_submit   # waterfall-relative
        segs = self.segments
        idx = PHASES.index(phase)
        if segs:
            last = segs[-1]
            if (last[0] == idx
                    and start - (last[1] + last[2]) < _COALESCE_GAP_S):
                last[2] = max(last[2], start + dur - last[1])
                return
        if len(segs) >= MAX_SEGMENTS:
            self.segments_dropped += 1
            return
        segs.append([idx, start, dur])

    def fair_decode(self, dur: float):
        """Fair-share book only: this request's share of one batched
        round (round wall / participants)."""
        self.decode_fair_s += dur

    def mark_first(self, now: float):
        """First token emitted: freeze the TTFT sub-book."""
        if self.t_first is None:
            self.t_first = now

    # -- queue bookkeeping -------------------------------------------------
    def queue_enter(self, now: float, reason: str = 'priority_queued'):
        """The request (re-)entered a scheduler queue."""
        self._q_mark = now
        self._q_reason = reason
        self._last_touch = now

    def queue_block(self, now: float, reason: str):
        """A scheduler pass sampled WHY this queued request is still
        waiting: the interval since the last mark books under the
        freshly sampled reason, and a new interval opens."""
        self._settle_queue(now, reason)
        self._q_mark = now
        self._q_reason = reason

    def queue_exit(self, now: float):
        """The request left the queue (seating attempt begins). No-op
        when not queued."""
        self._settle_queue(now, self._q_reason)
        self._q_mark = None

    def _settle_queue(self, now: float, reason: str):
        if self._q_mark is None:
            return
        dur = now - self._q_mark
        if dur > 0.0:
            self.add('queue_wait', dur, now=now)
            self.blocked[reason] = self.blocked.get(reason, 0.0) + dur

    def rebase_submit(self, t_submit: float):
        """Re-anchor the record at the ROUTER's submit instant: the gap
        between router entry and engine enqueue (QoS checks + replica
        pick) books as `admission`. Call before any segment exists on
        the engine clock would go stale — i.e. immediately after the
        first placement."""
        delta = self.t_submit - float(t_submit)
        if delta <= 0.0:
            return
        self.t_submit = float(t_submit)
        self.phases['admission'] += delta
        if self.t_first is None:
            self.ttft_phases['admission'] += delta
        for seg in self.segments:
            seg[1] += delta
        self.segments.insert(0, [PHASES.index('admission'), 0.0, delta])

    # -- views --------------------------------------------------------------
    def e2e_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def ttft_s(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    def summary(self, segments: bool = False) -> Dict[str, Any]:
        e2e = self.e2e_s()
        ttft = self.ttft_s()
        attributed = sum(self.phases.values())
        residual = overcount = 0.0
        if e2e is not None:
            residual = e2e - attributed
            overcount = max(-residual, 0.0)
            residual = max(residual, 0.0)
        t_resid = t_over = 0.0
        if ttft is not None:
            t_attr = sum(self.ttft_phases.values())
            t_resid = ttft - t_attr
            t_over = max(-t_resid, 0.0)
            t_resid = max(t_resid, 0.0)
        out = {
            'request_id': self.request_id,
            'tenant': self.tenant,
            'priority': self.priority,
            'adapter_id': self.adapter_id,
            'outcome': self.outcome,
            'tokens': self.tokens,
            'failovers': self.failovers,
            'replica_id': self.replica_id,
            'e2e_s': e2e,
            'ttft_s': ttft,
            'phases': {p: v for p, v in self.phases.items() if v > 0.0},
            'ttft_phases': {p: v for p, v in self.ttft_phases.items()
                            if v > 0.0},
            'blocked': dict(self.blocked),
            'decode_fair_s': self.decode_fair_s,
            'residual_s': residual,
            'overcount_s': overcount,
            'ttft_residual_s': t_resid,
            'ttft_overcount_s': t_over,
            'wall_ts': self.wall_ts,
            # submit instant on the span clock (events._now timeline):
            # stitch_trace projects segments through the same per-process
            # skew offset every span rides
            'ts': self.t_submit - _events._EPOCH,
        }
        if segments:
            out['segments'] = [
                {'phase': PHASES[int(i)], 'start_s': round(s, 6),
                 'dur_s': round(d, 6)}
                for i, s, d in self.segments]
            out['segments_dropped'] = self.segments_dropped
        return out


def _quantile(sorted_vals: Sequence[float], p: float) -> Optional[float]:
    if not sorted_vals:
        return None
    return sorted_vals[min(int(p * len(sorted_vals)),
                           len(sorted_vals) - 1)]


class RequestLedger:
    """Aggregates finalized `RequestRecord`s; see module docstring.

    Thread model: records mutate un-locked on their driving thread;
    everything the ledger itself holds mutates under `_lock`
    (finalize arrives from engine/router/mirror threads, report() from
    scrape threads).

    Args:
        window_s: sliding window for the slowest-K exemplars and the
            p50/p99 decomposition.
        top_k: slowest exemplars (full waterfalls) kept per window.
        reservoir: reservoir-sampled exemplars kept alongside.
        slow_factor: `request_slow` fires when TTFT exceeds
            slow_factor x the SLO TTFT objective.
        slow_ttft_s: explicit SLO TTFT; None reads the registered
            SLOEngine's `ttft_p99` objective at finalize time.
    """

    _window = _concurrency.guarded_by('_lock', mutable=True)
    _slowest = _concurrency.guarded_by('_lock', mutable=True)
    _reservoir = _concurrency.guarded_by('_lock', mutable=True)
    _wire_buf = _concurrency.guarded_by('_lock', mutable=True)

    WINDOW_MAX = 4096
    WIRE_BUF_MAX = 2048

    def __init__(self, window_s: float = 300.0, top_k: int = 16,
                 reservoir: int = 64, slow_factor: float = 3.0,
                 slow_ttft_s: Optional[float] = None):
        self.window_s = float(window_s)
        self.top_k = int(top_k)
        self.reservoir_cap = int(reservoir)
        self.slow_factor = float(slow_factor)
        self.slow_ttft_s = slow_ttft_s
        self._lock = _concurrency.Lock('RequestLedger._lock')
        self._enabled = True
        self._window: List[Dict[str, Any]] = []
        self._slowest: List[Dict[str, Any]] = []
        self._reservoir: List[Dict[str, Any]] = []
        self._wire_buf: List[Dict[str, Any]] = []
        self._wire_dropped = 0
        self._res_seen = 0
        self._rng = random.Random(0x5eed)
        self._totals: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
        self._blocked_totals: Dict[str, float] = {}
        self._residual_total = 0.0
        self._overcount_total = 0.0
        self._decode_fair_total = 0.0
        self._engine_decode_wall_s = 0.0
        self._finished = 0
        self._slow_count = 0

    # -- lifecycle -----------------------------------------------------------
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def enable(self) -> 'RequestLedger':
        self._enabled = True
        return self

    def disable(self) -> 'RequestLedger':
        """Stop opening records (the A/B bench's off arm). In-flight
        records keep accumulating and still finalize."""
        self._enabled = False
        return self

    def reset(self):
        with self._lock:
            self._window.clear()
            self._slowest.clear()
            self._reservoir.clear()
            self._wire_buf.clear()
            self._wire_dropped = 0
            self._res_seen = 0
            self._totals = dict.fromkeys(PHASES, 0.0)
            self._blocked_totals = {}
            self._residual_total = 0.0
            self._overcount_total = 0.0
            self._decode_fair_total = 0.0
            self._engine_decode_wall_s = 0.0
            self._finished = 0
            self._slow_count = 0

    # -- record creation / engine helpers ------------------------------------
    def open(self, request_id: int, t_submit: float,
             tenant: Optional[str] = None, priority: Optional[int] = None,
             adapter_id: Optional[str] = None) -> Optional[RequestRecord]:
        if not self._enabled:
            return None
        rec = RequestRecord(request_id, t_submit, tenant=tenant,
                            priority=priority, adapter_id=adapter_id)
        rec._owner = self
        return rec

    def open_for(self, handle) -> Optional[RequestRecord]:
        """Create + attach a record for a request handle (engine submit
        path). Returns None while disabled."""
        rec = self.open(handle.request_id, handle._t_submit,
                        priority=getattr(handle, 'priority', None),
                        adapter_id=getattr(handle, 'adapter_id', None))
        handle._ledger_rec = rec
        return rec

    def note_round(self, dur: float, records: Sequence[RequestRecord],
                   phase: str = 'decode', now: Optional[float] = None,
                   absorb: bool = False):
        """One batched decode/speculation round of wall `dur` with these
        participants: waterfall book charges each the FULL round wall,
        fair-share book splits it evenly, and the engine decode wall
        accumulates once — the two closure invariants' raw material.

        `absorb=True` additionally charges each participant the idle
        gap since its record was last touched (a single-threaded driver
        serializes replicas, so an active request waits out the OTHER
        replicas' rounds between its own — that wait is part of its
        decode period, and leaving it in the residual would break the
        1% closure the tier-1 tests pin). The fair-share book never
        absorbs: it stays round_wall / n so it closes to the engine
        decode wall, not the driver wall."""
        recs = [r for r in records if r is not None]
        if dur <= 0.0 or not recs:
            return
        end = time.perf_counter() if now is None else now
        share = dur / len(recs)
        for rec in recs:
            d = dur
            if absorb:
                d = max(dur, end - rec._last_touch)
            rec.add(phase, d, now=end)
            rec.fair_decode(share)
        with self._lock:
            self._engine_decode_wall_s += dur

    def note_prefill(self, dur: float, owner: Optional[RequestRecord],
                     seated: Sequence[RequestRecord],
                     now: Optional[float] = None):
        """One prefill (whole or chunk) of wall `dur`: the owner books
        `prefill`; every OTHER seated request books `prefill_wait` —
        the chunked-prefill convoy, named instead of smeared. Like
        `note_round(absorb=True)`, each participant also absorbs the
        idle gap since its last touch (per-chunk python dispatch
        overhead between spans would otherwise pile into residuals)."""
        if dur <= 0.0:
            return
        end = time.perf_counter() if now is None else now
        if owner is not None:
            owner.add('prefill', max(dur, end - owner._last_touch),
                      now=end)
        for rec in seated:
            if rec is not None and rec is not owner:
                rec.add('prefill_wait',
                        max(dur, end - rec._last_touch), now=end)

    def engine_decode_wall_s(self) -> float:
        with self._lock:
            return self._engine_decode_wall_s

    # -- finalize -------------------------------------------------------------
    def finalize(self, handle, now: Optional[float] = None,
                 outcome: Optional[str] = None):
        """Close a handle's record into the books (idempotent: the first
        caller wins — engine retire, mirror update, or router reap).
        Routes to the record's OWNING ledger, so handle hooks can always
        call through the default singleton."""
        rec = getattr(handle, '_ledger_rec', None)
        if rec is None:
            return
        (rec._owner or self).finalize_record(
            rec,
            now=now if now is not None else getattr(handle, '_t_done',
                                                    None),
            outcome=outcome,
            tokens=len(getattr(handle, 'tokens', ()) or ()))

    def finalize_record(self, rec: RequestRecord,
                        now: Optional[float] = None,
                        outcome: Optional[str] = None, tokens: int = 0):
        if rec.t_done is not None:
            return   # already closed (failover/reap double-report)
        end = time.perf_counter() if now is None else now
        rec.queue_exit(end)   # a failed request may die still queued
        rec.t_done = end
        rec.outcome = outcome or 'completed'
        rec.tokens = int(tokens)
        rec.wall_ts = time.time()
        summ = rec.summary()
        wf = rec.summary(segments=True)
        with self._lock:
            self._finished += 1
            for p, v in rec.phases.items():
                self._totals[p] += v
            for r, v in rec.blocked.items():
                self._blocked_totals[r] = \
                    self._blocked_totals.get(r, 0.0) + v
            self._residual_total += summ['residual_s']
            self._overcount_total += summ['overcount_s']
            self._decode_fair_total += rec.decode_fair_s
            self._window.append(summ)
            if len(self._window) > self.WINDOW_MAX:
                del self._window[:len(self._window) - self.WINDOW_MAX]
            self._res_seen += 1
            self._note_exemplar(wf)
            if len(self._wire_buf) < self.WIRE_BUF_MAX:
                self._wire_buf.append(wf)
            else:
                self._wire_dropped += 1
        self._maybe_slow(summ)

    def _note_exemplar(self, wf: Dict[str, Any]):
        # caller holds self._lock and has already counted this record
        # into _res_seen (the reservoir's 1-indexed item number)
        horizon = wf['wall_ts'] - self.window_s
        self._slowest = [w for w in self._slowest
                         if w['wall_ts'] >= horizon]
        self._slowest.append(wf)
        self._slowest.sort(key=lambda w: -(w['e2e_s'] or 0.0))
        del self._slowest[self.top_k:]
        if len(self._reservoir) < self.reservoir_cap:
            self._reservoir.append(wf)
        else:
            j = self._rng.randrange(self._res_seen)
            if j < self.reservoir_cap:
                self._reservoir[j] = wf

    def _slow_threshold_s(self) -> Optional[float]:
        base = self.slow_ttft_s
        if base is None:
            from .slo import get_engine
            eng = get_engine()
            if eng is not None:
                for o in getattr(eng, 'objectives', ()):
                    if o.kind == 'latency_p99' and 'ttft' in o.name:
                        base = o.threshold_s
                        break
        if base is None:
            return None
        return base * self.slow_factor

    def _maybe_slow(self, summ: Dict[str, Any]):
        thr = self._slow_threshold_s()
        ttft = summ['ttft_s']
        if thr is None or ttft is None or ttft <= thr:
            return
        phases = summ['ttft_phases'] or summ['phases']
        driver = max(phases, key=phases.get) if phases else 'residual'
        with self._lock:
            self._slow_count += 1
        # one pathological request captures its own postmortem: the
        # flight recorder triggers on this event and bundles
        # requests.json alongside the trace tail
        _events.emit('request_slow', request_id=summ['request_id'],
                     tenant=summ['tenant'], ttft_s=round(ttft, 4),
                     threshold_s=round(thr, 4), driver=driver,
                     failovers=summ['failovers'])

    # -- wire plane -----------------------------------------------------------
    def drain_wire_records(self) -> List[Dict[str, Any]]:
        """Hand the finalized-record backlog to the Shipper (each call
        drains; re-ship idempotence rides the segment seq, as for every
        other kind)."""
        with self._lock:
            out, self._wire_buf = self._wire_buf, []
            return out

    # -- the books ------------------------------------------------------------
    def report(self, top: Optional[int] = None,
               now: Optional[float] = None) -> Dict[str, Any]:
        """The `/requests` payload: per-phase decomposition percentiles
        over the window, the p99-driver ranking, blocked-reason ranking,
        slowest-K waterfalls + reservoir exemplars, closure totals."""
        wall_now = time.time() if now is None else now
        horizon = wall_now - self.window_s
        with self._lock:
            window = [s for s in self._window
                      if (s['wall_ts'] or 0.0) >= horizon]
            slowest = [dict(w) for w in self._slowest
                       if w['wall_ts'] >= horizon]
            exemplars = [dict(w) for w in self._reservoir]
            totals = dict(self._totals)
            blocked = dict(self._blocked_totals)
            closure = {
                'finished': self._finished,
                'attributed_s': sum(self._totals.values()),
                'residual_s': self._residual_total,
                'overcount_s': self._overcount_total,
                'decode_fair_s': self._decode_fair_total,
                'engine_decode_wall_s': self._engine_decode_wall_s,
                'slow_requests': self._slow_count,
                'wire_records_dropped': self._wire_dropped,
            }
        e2es = sorted(s['e2e_s'] for s in window
                      if s['e2e_s'] is not None)
        ttfts = sorted(s['ttft_s'] for s in window
                       if s['ttft_s'] is not None)
        decomposition = {}
        for p in PHASES + ('residual',):
            vals = sorted((s['phases'].get(p, 0.0) if p != 'residual'
                           else s['residual_s']) for s in window)
            if vals and vals[-1] > 0.0:
                decomposition[p] = {
                    'p50_s': _quantile(vals, 0.50),
                    'p99_s': _quantile(vals, 0.99),
                    'mean_s': sum(vals) / len(vals),
                }
        # p99 driver: among the tail cohort (e2e >= p99), which phase
        # holds the most seconds — the ranking IS the answer to "where
        # did my p99 go"
        driver_ranking: List[Dict[str, Any]] = []
        p99_driver = None
        p99 = _quantile(e2es, 0.99)
        if p99 is not None:
            tail = [s for s in window
                    if s['e2e_s'] is not None and s['e2e_s'] >= p99]
            sums: Dict[str, float] = {}
            for s in tail:
                for p, v in s['phases'].items():
                    sums[p] = sums.get(p, 0.0) + v
                sums['residual'] = sums.get('residual', 0.0) \
                    + s['residual_s']
            total = sum(sums.values()) or 1.0
            driver_ranking = [
                {'phase': p, 'seconds': v, 'share': v / total}
                for p, v in sorted(sums.items(), key=lambda kv: -kv[1])
                if v > 0.0]
            if driver_ranking:
                p99_driver = driver_ranking[0]['phase']
        blocked_ranking = [
            {'reason': r, 'seconds': v}
            for r, v in sorted(blocked.items(), key=lambda kv: -kv[1])]
        return {
            'enabled': self._enabled,
            'window_s': self.window_s,
            'window_requests': len(window),
            'e2e_p50_s': _quantile(e2es, 0.50),
            'e2e_p99_s': p99,
            'ttft_p50_s': _quantile(ttfts, 0.50),
            'ttft_p99_s': _quantile(ttfts, 0.99),
            'phases': decomposition,
            'p99_driver': p99_driver,
            'p99_driver_ranking': driver_ranking,
            'blocked_ranking': blocked_ranking,
            'phase_totals': totals,
            'blocked_totals': blocked,
            'closure': closure,
            'slowest': slowest[:top] if top is not None else slowest,
            'exemplars': exemplars,
        }


_ledger = RequestLedger()


def get_ledger() -> RequestLedger:
    return _ledger


def enabled() -> bool:
    """Instrumentation-site fast path: is the default ledger opening
    records right now?"""
    return _ledger._enabled


def _reqledger_collector(reg: '_metrics.MetricsRegistry'):
    """Scrape-time mirror of the default ledger (mirror, not accumulate
    — the contract every collector follows). Residual rides the phase
    label so `sum(paddle_request_phase_seconds_total)` is the fleet's
    total accounted request time."""
    with _ledger._lock:
        totals = dict(_ledger._totals)
        blocked = dict(_ledger._blocked_totals)
        residual = _ledger._residual_total
        overcount = _ledger._overcount_total
        fair = _ledger._decode_fair_total
        wall = _ledger._engine_decode_wall_s
        finished = _ledger._finished
        slow = _ledger._slow_count
    secs = reg.counter('paddle_request_phase_seconds_total',
                       'seconds attributed per request-ledger phase '
                       'across finished requests', ('phase',))
    for p, v in list(totals.items()) + [('residual', residual)]:
        secs.labels(phase=p).value = max(float(v), 0.0)   # mirror
    blk = reg.counter('paddle_request_queue_blocked_seconds_total',
                      'queue_wait seconds partitioned by the sampled '
                      'blocking reason', ('reason',))
    for r, v in blocked.items():
        blk.labels(reason=r).value = max(float(v), 0.0)   # mirror
    reg.counter('paddle_requests_finished_total',
                'requests finalized into the request ledger'
                )._sole().value = float(finished)          # mirror
    reg.counter('paddle_requests_slow_total',
                'requests whose TTFT crossed the request_slow '
                'threshold (N x SLO)')._sole().value = float(slow)
    reg.gauge('paddle_request_overcount_seconds',
              'attributed request seconds beyond measured E2E '
              '(clipped out of residuals)').set(overcount)
    reg.counter('paddle_request_decode_fair_seconds_total',
                'fair-share decode seconds across finished requests '
                '(sums to the engine decode wall)'
                )._sole().value = max(float(fair), 0.0)    # mirror
    reg.counter('paddle_request_decode_wall_seconds_total',
                'engine decode/speculation round wall seconds the '
                'ledger observed')._sole().value = \
        max(float(wall), 0.0)                              # mirror


def install():
    """Idempotent: register the default ledger's scrape-time collector
    (runs at package import; the ledger itself is always on)."""
    _metrics.get_registry().register_collector(_reqledger_collector)
