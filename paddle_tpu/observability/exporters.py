"""Exporters: Prometheus text, JSONL, chrome://tracing JSON.

One registry + one event log, three standard surfaces: a Prometheus
scrape body for fleet dashboards, JSONL lines for plain-file tailing
(the same format utils.logging.SummaryWriter writes), and a
chrome-trace with TRUE per-event begin timestamps and durations
(consumable by Perfetto/chrome://tracing next to the device-side trace
jax.profiler writes).
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry, get_registry


def _fmt_labels(labels: Dict[str, str], extra: Dict[str, str]) -> str:
    all_labels = {**labels, **extra}
    if not all_labels:
        return ''
    body = ','.join(f'{k}="{_escape(str(v))}"'
                    for k, v in sorted(all_labels.items()))
    return '{' + body + '}'


def _escape(v: str) -> str:
    """Label-value escaping: backslash, double-quote, line feed."""
    return v.replace('\\', '\\\\').replace('"', '\\"').replace('\n', '\\n')


def _escape_help(v: str) -> str:
    """HELP-text escaping per the exposition format: ONLY backslash and
    line feed — escaping quotes here would corrupt the help text."""
    return v.replace('\\', '\\\\').replace('\n', '\\n')


def _num(v: float) -> str:
    f = float(v)
    if f != f:
        return 'NaN'
    if f in (float('inf'), float('-inf')):
        return '+Inf' if f > 0 else '-Inf'
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus exposition format (text/plain; version 0.0.4). Every
    sample carries a `process` label with the host's process index so
    multi-host scrapes aggregate cleanly."""
    registry = registry if registry is not None else get_registry()
    snap = registry.snapshot()
    proc = {'process': str(snap['process_index'])}
    lines = []
    for m in snap['metrics']:
        name = m['name']
        lines.append(f'# HELP {name} {_escape_help(m["help"])}')
        lines.append(f'# TYPE {name} {m["type"]}')
        for s in m['samples']:
            if m['type'] == 'histogram':
                for bound, count in s['buckets'].items():
                    lines.append(
                        f'{name}_bucket'
                        f'{_fmt_labels(s["labels"], {**proc, "le": bound})}'
                        f' {count}')
                lines.append(f'{name}_sum{_fmt_labels(s["labels"], proc)}'
                             f' {_num(s["sum"])}')
                lines.append(f'{name}_count{_fmt_labels(s["labels"], proc)}'
                             f' {s["count"]}')
            else:
                lines.append(f'{name}{_fmt_labels(s["labels"], proc)}'
                             f' {_num(s["value"])}')
        if m['type'] == 'histogram':
            # windowed quantiles ride a SEPARATE gauge family ({name}_wq
            # with a `quantile` label) — a histogram family must carry
            # only _bucket/_sum/_count samples to stay conformant, and a
            # distinct name keeps promtool/Grafana happy while /summary
            # and dashboards get true trailing-window percentiles
            qlines = []
            for s in m['samples']:
                for q, v in sorted((s.get('quantiles') or {}).items()):
                    qlines.append(
                        f'{name}_wq'
                        f'{_fmt_labels(s["labels"], {**proc, "quantile": q})}'
                        f' {_num(v)}')
            if qlines:
                lines.append(f'# HELP {name}_wq trailing-window quantiles '
                             f'of {name}')
                lines.append(f'# TYPE {name}_wq gauge')
                lines.extend(qlines)
    return '\n'.join(lines) + '\n'


def to_jsonl(registry: Optional[MetricsRegistry] = None,
             path: Optional[str] = None) -> str:
    """One JSON line per sample: {name, type, labels, process, value |
    sum/count/buckets} — the plain-file surface per-host fleet logs use."""
    registry = registry if registry is not None else get_registry()
    snap = registry.snapshot()
    lines = []
    for m in snap['metrics']:
        for s in m['samples']:
            rec = {'name': m['name'], 'type': m['type'],
                   'labels': s['labels'],
                   'process': snap['process_index']}
            if m['type'] == 'histogram':
                rec.update(sum=s['sum'], count=s['count'],
                           buckets=s['buckets'])
            else:
                rec['value'] = s['value']
            lines.append(json.dumps(rec))
    text = '\n'.join(lines)
    if text:
        text += '\n'
    if path is not None:
        with open(path, 'w') as f:
            f.write(text)
    return text


def read_jsonl(text_or_path: str):
    """Parse a to_jsonl export back into records (path or raw text)."""
    if '\n' not in text_or_path and not text_or_path.lstrip().startswith(
            '{'):
        with open(text_or_path) as f:
            text = f.read()
    else:
        text = text_or_path
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def chrome_track_metadata(pid: int, process_name: str,
                          tids: Optional[Dict[int, str]] = None,
                          sort_index: Optional[int] = None):
    """Chrome-trace 'M' (metadata) events naming one process track and
    its threads — without these, Perfetto/chrome://tracing renders bare
    pid/tid integers, which is useless the moment a stitched fleet trace
    has one track per process."""
    events = [{'name': 'process_name', 'ph': 'M', 'pid': pid, 'tid': 0,
               'args': {'name': process_name}}]
    if sort_index is not None:
        events.append({'name': 'process_sort_index', 'ph': 'M', 'pid': pid,
                       'tid': 0, 'args': {'sort_index': int(sort_index)}})
    for tid, tname in sorted((tids or {}).items()):
        events.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                       'tid': tid, 'args': {'name': tname}})
    return events


def to_chrome_trace(event_log=None, path: Optional[str] = None
                    ) -> Dict[str, Any]:
    """chrome://tracing JSON built from the EventLog's REAL timestamps:
    each span becomes a complete ('X') event at its actual begin time
    with its actual duration; instant events ('i') keep their timestamp.
    Timestamps are microseconds on the process-wide span clock. Track
    metadata ('M') events label the process track and every live thread
    by its Python thread name."""
    import threading
    from .events import get_event_log
    from .metrics import get_registry
    # `is None`, not truthiness: an empty EventLog is falsy (__len__)
    event_log = get_event_log() if event_log is None else event_log
    thread_names = {t.ident: t.name for t in threading.enumerate()
                    if t.ident is not None}
    trace_events = []
    seen_tids = set()
    for e in event_log.events():
        out = {'name': e['name'], 'ph': e.get('ph', 'X'), 'pid': 0,
               'tid': e.get('tid', 0), 'ts': int(e['ts'] * 1e6)}
        seen_tids.add(out['tid'])
        if out['ph'] == 'X':
            out['dur'] = int(e.get('dur', 0.0) * 1e6)
        if out['ph'] == 'i':
            out['s'] = 't'   # instant scope: thread
        args = dict(e.get('attrs') or {})
        if 'depth' in e:
            args['depth'] = e['depth']
        if args:
            out['args'] = args
        trace_events.append(out)
    proc_name = f'paddle_tpu process {get_registry().process_index()}'
    meta = chrome_track_metadata(
        0, proc_name,
        {tid: thread_names.get(tid, f'thread-{tid}')
         for tid in sorted(seen_tids)})
    doc = {'traceEvents': meta + trace_events, 'displayTimeUnit': 'ms'}
    if path is not None:
        with open(path, 'w') as f:
            json.dump(doc, f)
    return doc


def fleet_to_prometheus_text(aggregator) -> str:
    """Prometheus exposition of an Aggregator's fleet view: the merged
    samples labeled `process="fleet"`, then each per-process state's
    samples labeled with its process_uid — the `/fleet/metrics` body.
    This is where `paddle_events_dropped_total{process=...}` becomes a
    per-process labeled series (locally it is unlabeled — existing
    single-process scrapes depend on that)."""
    sections = [('fleet', aggregator.merged())]
    sections.extend(sorted(aggregator.per_process_snapshots().items()))
    # group by family across sections: the exposition format wants all
    # of a metric's lines contiguous under one HELP/TYPE
    families: Dict[str, Dict[str, Any]] = {}
    for proc_label, snap in sections:
        for m in snap.get('metrics', []):
            fam = families.setdefault(m['name'], {
                'type': m['type'], 'help': m['help'], 'rows': []})
            fam['rows'].extend((proc_label, s) for s in m['samples'])
    lines = []
    for name, fam in families.items():
        lines.append(f'# HELP {name} {_escape_help(fam["help"])}')
        lines.append(f'# TYPE {name} {fam["type"]}')
        for proc_label, s in fam['rows']:
            proc = {'process': str(proc_label)}
            if fam['type'] == 'histogram':
                for bound, count in s['buckets'].items():
                    lines.append(
                        f'{name}_bucket'
                        f'{_fmt_labels(s["labels"], {**proc, "le": bound})}'
                        f' {count}')
                lines.append(f'{name}_sum{_fmt_labels(s["labels"], proc)}'
                             f' {_num(s["sum"])}')
                lines.append(f'{name}_count{_fmt_labels(s["labels"], proc)}'
                             f' {s["count"]}')
            else:
                lines.append(f'{name}{_fmt_labels(s["labels"], proc)}'
                             f' {_num(s["value"])}')
    return '\n'.join(lines) + '\n'
