"""Thread-safe metrics registry: labeled Counter / Gauge / Histogram.

Upstream Paddle scatters runtime telemetry across paddle.profiler
summaries, FLAGS_check_nan_inf prints, and fleet's per-worker logs. The
TPU-native framework centralizes all of it in ONE process-wide
`MetricsRegistry` that every subsystem reports into (dispatch cache, jit
compiles, collectives, optimizer offload, hapi step telemetry), so a
single snapshot/export answers "where did this step's time, bytes, and
compiles go" — the MegaScale-style observability substrate
(arXiv:2402.15627) the ROADMAP's pod-scale north star assumes.

Design rules:
- Hot paths never pay for observability: per-op counters (the eager
  dispatch cache) stay raw ints in their own module and flow into the
  registry through *collectors* — callbacks run at snapshot/export time,
  not per event. Direct metric writes are reserved for per-step /
  per-collective / per-compile frequency events.
- Metric families are create-or-get by name (idempotent), children are
  create-or-get by label values, and every mutation takes one registry
  RLock — cheap at the rates we write, safe under DataLoader workers.
- `snapshot()` is plain data (JSON-able) and carries the host's
  process_index so multi-host fleets can gather and merge registries
  over the existing collectives (fleet_utils.gather_registry).
"""
from __future__ import annotations

import bisect
import collections
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import flags as _flags
from ..analysis.runtime import concurrency as _concurrency

_flags.register_flag('FLAGS_observability', True)

_enabled = [bool(_flags.flag('FLAGS_observability'))]


def enabled() -> bool:
    """Fast global gate consulted by instrumented call sites."""
    return _enabled[0]


def enable(on: bool = True):
    """Toggle direct metric writes (spans, step telemetry, collective /
    offload / compile counters). Collectors still report at snapshot
    time — they read state that exists anyway."""
    _enabled[0] = bool(on)
    _flags.set_flags({'FLAGS_observability': bool(on)})


def disable():
    enable(False)


# latency-shaped default buckets (seconds), Prometheus-style
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0,
                   5.0, 10.0, 60.0)

# windowed quantile sketch: every histogram keeps its last N raw
# observations so /summary can render true p50/p95/p99 (serving
# TTFT/TPOT, step time) without Prometheus-side bucket interpolation
QUANTILE_WINDOW = 256
QUANTILES = (0.5, 0.95, 0.99)


def _q_key(q: float) -> str:
    """Quantile label value: '0.5', '0.95', '0.99' (no float noise)."""
    return f'{q:g}'


def _label_key(labelnames: Sequence[str], labels: Dict[str, Any]
               ) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f'expected labels {tuple(labelnames)}, got {tuple(labels)}')
    return tuple(str(labels[n]) for n in labelnames)


class Counter:
    """Monotonically increasing value (one child of a family)."""

    __slots__ = ('_family', '_labels', 'value')

    def __init__(self, family, labels: Tuple[str, ...]):
        self._family = family
        self._labels = labels
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError(f'counters only go up; inc({amount})')
        with self._family._registry._lock:
            self.value += amount
        return self


class Gauge:
    """Point-in-time value (one child of a family)."""

    __slots__ = ('_family', '_labels', 'value')

    def __init__(self, family, labels: Tuple[str, ...]):
        self._family = family
        self._labels = labels
        self.value = 0.0

    def set(self, value: float):
        with self._family._registry._lock:
            self.value = float(value)
        return self

    def inc(self, amount: float = 1.0):
        with self._family._registry._lock:
            self.value += amount
        return self

    def dec(self, amount: float = 1.0):
        return self.inc(-amount)

    def set_to_max(self, value: float):
        """Watermark update: keep the max of the current and new value."""
        with self._family._registry._lock:
            if value > self.value:
                self.value = float(value)
        return self


class Histogram:
    """Cumulative-bucket distribution (one child of a family)."""

    __slots__ = ('_family', '_labels', 'bucket_counts', 'sum', 'count',
                 '_window')

    def __init__(self, family, labels: Tuple[str, ...]):
        self._family = family
        self._labels = labels
        self.bucket_counts = [0] * (len(family.buckets) + 1)  # +inf last
        self.sum = 0.0
        self.count = 0
        # trailing raw observations for windowed quantiles (p50/p95/p99)
        self._window: collections.deque = collections.deque(
            maxlen=QUANTILE_WINDOW)

    def observe(self, value: float):
        v = float(value)
        if not math.isfinite(v):
            # a single NaN observation would poison `sum` forever (e.g.
            # a NaN loss observed before the FT rollback drops the
            # batch); drop it but keep the drop itself visible
            self._family._registry.counter(
                'paddle_metrics_nonfinite_dropped_total',
                'non-finite histogram observations dropped',
                ('metric',)).labels(metric=self._family.name).inc()
            return self
        with self._family._registry._lock:
            self.bucket_counts[bisect.bisect_left(
                self._family.buckets, v)] += 1
            self.sum += v
            self.count += 1
            self._window.append(v)
        return self

    def window_quantiles(self, qs: Sequence[float] = QUANTILES
                         ) -> Dict[str, float]:
        """Quantiles over the trailing observation window (nearest-rank
        on up to QUANTILE_WINDOW raw samples). Empty dict before the
        first observation — an absent percentile is honest, a fabricated
        zero is not."""
        with self._family._registry._lock:
            vals = sorted(self._window)
        if not vals:
            return {}
        n = len(vals)
        return {_q_key(q): vals[min(int(q * n), n - 1)] for q in qs}


class SlidingWindow:
    """Time-windowed observation buffer: quantiles, count, and rate over
    the trailing `window_s` seconds of real time.

    The Histogram's quantile sketch is COUNT-windowed (last 256
    observations) — fine for "what did recent steps look like", useless
    for a control loop: after a burst ends, those 256 stale samples keep
    reporting the burst for however long traffic stays quiet. An
    autoscaler needs signals that age out by the clock, so this buffer
    keeps (timestamp, value) pairs and prunes everything older than the
    window on every read and write. `maxlen` bounds memory under
    pathological observation rates (oldest drop first — under that much
    traffic the window is saturated anyway); `clock` is injectable for
    deterministic tests.

    Thread-safe; cheap enough for per-request/per-round observation
    (one deque append + amortized prune)."""

    __slots__ = ('window_s', '_clock', '_obs', '_lock')

    def __init__(self, window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 maxlen: int = 8192):
        if window_s <= 0:
            raise ValueError('window_s must be positive')
        self.window_s = float(window_s)
        self._clock = clock
        self._obs: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = _concurrency.Lock('SlidingWindow._lock')

    def _prune(self, now: float):
        cutoff = now - self.window_s
        obs = self._obs
        while obs and obs[0][0] < cutoff:
            obs.popleft()

    def observe(self, value: float):
        now = self._clock()
        with self._lock:
            self._prune(now)
            self._obs.append((now, float(value)))
        return self

    def mark(self):
        """Record an occurrence (value 1.0) — the rate()-only use case
        (shed events, admissions)."""
        return self.observe(1.0)

    def values(self) -> List[float]:
        with self._lock:
            self._prune(self._clock())
            return [v for _, v in self._obs]

    def count(self) -> int:
        with self._lock:
            self._prune(self._clock())
            return len(self._obs)

    def rate(self) -> float:
        """Observations per second over the window."""
        return self.count() / self.window_s

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile over the in-window values, or None when
        the window is empty — an absent percentile is honest, a
        fabricated zero is not (same contract as window_quantiles)."""
        vals = sorted(self.values())
        if not vals:
            return None
        n = len(vals)
        return vals[min(int(q * n), n - 1)]

    def quantiles(self, qs: Sequence[float] = QUANTILES
                  ) -> Dict[str, float]:
        vals = sorted(self.values())
        if not vals:
            return {}
        n = len(vals)
        return {_q_key(q): vals[min(int(q * n), n - 1)] for q in qs}

    def mean(self) -> Optional[float]:
        vals = self.values()
        return sum(vals) / len(vals) if vals else None


_CHILD_TYPES = {'counter': Counter, 'gauge': Gauge, 'histogram': Histogram}


class _Family:
    """One named metric: a set of children keyed by label values. With no
    labelnames the family proxies its single child, so
    `reg.counter('x').inc()` works without a labels() hop."""

    def __init__(self, registry: 'MetricsRegistry', name: str, typ: str,
                 help: str, labelnames: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]] = None):
        self._registry = registry
        self.name = name
        self.type = typ
        self.help = help
        self.labelnames = labelnames
        if typ == 'histogram':
            self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not labelnames:
            self._children[()] = _CHILD_TYPES[typ](self, ())

    def labels(self, **labels):
        key = _label_key(self.labelnames, labels)
        with self._registry._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _CHILD_TYPES[self.type](
                    self, key)
        return child

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        """Locked snapshot of (label-values, child) pairs. Readers on
        scrape/summary/listener threads iterate THIS, never `_children`
        directly: `labels()` on another thread (a router scaling up
        mints a new replica's gauge child mid-scrape) grows the dict,
        and an unlocked iteration dies with "dictionary changed size
        during iteration"."""
        with self._registry._lock:
            return list(self._children.items())

    def total(self) -> float:
        """Locked sum of every child's value (labeled counter/gauge
        families; the headline-view aggregation)."""
        with self._registry._lock:
            return sum(c.value for c in self._children.values())

    def _sole(self):
        if self.labelnames:
            raise ValueError(
                f'{self.name} is labeled {self.labelnames}; use .labels()')
        return self._children[()]

    # unlabeled convenience proxies
    def inc(self, amount: float = 1.0):
        return self._sole().inc(amount)

    def set(self, value: float):
        return self._sole().set(value)

    def dec(self, amount: float = 1.0):
        return self._sole().dec(amount)

    def set_to_max(self, value: float):
        return self._sole().set_to_max(value)

    def observe(self, value: float):
        return self._sole().observe(value)

    @property
    def value(self):
        return self._sole().value

    @property
    def count(self):
        return self._sole().count

    @property
    def sum(self):
        return self._sole().sum


class MetricsRegistry:
    def __init__(self, process_index: Optional[int] = None):
        self._lock = _concurrency.RLock('MetricsRegistry._lock')
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[['MetricsRegistry'], None]] = []
        self._process_index = process_index
        self._in_collect = False

    # -- family constructors (create-or-get, conflict-checked) --------------
    def _family(self, name, typ, help, labelnames, buckets=None):
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    self, name, typ, help, labelnames, buckets)
            elif fam.type != typ or fam.labelnames != labelnames:
                raise ValueError(
                    f'metric {name!r} already registered as {fam.type}'
                    f'{fam.labelnames}; asked for {typ}{labelnames}')
            return fam

    def counter(self, name, help: str = '', labelnames: Sequence[str] = ()):
        return self._family(name, 'counter', help, labelnames)

    def gauge(self, name, help: str = '', labelnames: Sequence[str] = ()):
        return self._family(name, 'gauge', help, labelnames)

    def histogram(self, name, help: str = '',
                  labelnames: Sequence[str] = (), buckets=None):
        return self._family(name, 'histogram', help, labelnames, buckets)

    # -- collectors ----------------------------------------------------------
    def register_collector(self, fn: Callable[['MetricsRegistry'], None]):
        """`fn(registry)` runs at snapshot/export time to sync state that
        is kept outside the registry (e.g. the dispatch cache's raw
        counters) into registry metrics — the zero-hot-path-cost path."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def _collect(self):
        with self._lock:
            if self._in_collect:   # a collector snapshotting would recurse
                return
            self._in_collect = True
            try:
                for fn in list(self._collectors):
                    try:
                        fn(self)
                    except Exception:
                        # a broken collector must not kill a scrape, but
                        # its absence from the exposition must be
                        # countable (RLock: safe to create the family
                        # mid-collect)
                        count_suppressed('metrics_collector', self)
            finally:
                self._in_collect = False

    # -- introspection -------------------------------------------------------
    def process_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        try:
            import jax
            return int(jax.process_index())
        except Exception:  # paddle-lint: disable=swallowed-exception -- jax absent/pre-init: process 0 is the single-host answer
            return 0

    def get(self, name) -> Optional[_Family]:
        return self._families.get(name)

    def value(self, name, default=0.0, **labels) -> float:
        """Read one sample's current value (counters/gauges); collectors
        are NOT run — pair with snapshot() for collected reads."""
        fam = self._families.get(name)
        if fam is None:
            return default
        key = _label_key(fam.labelnames, labels) if labels else ()
        child = fam._children.get(key)
        return default if child is None else child.value

    def snapshot(self) -> Dict[str, Any]:
        """Collected, JSON-able view of every metric."""
        self._collect()
        with self._lock:
            metrics = []
            for fam in self._families.values():
                samples = []
                for key, child in fam._children.items():
                    labels = dict(zip(fam.labelnames, key))
                    if fam.type == 'histogram':
                        samples.append({
                            'labels': labels, 'sum': child.sum,
                            'count': child.count,
                            'buckets': dict(zip(
                                [str(b) for b in fam.buckets] + ['+Inf'],
                                _cumulate(child.bucket_counts))),
                            'quantiles': child.window_quantiles()})
                    else:
                        samples.append({'labels': labels,
                                        'value': child.value})
                entry = {'name': fam.name, 'type': fam.type,
                         'help': fam.help, 'samples': samples}
                if fam.type == 'histogram':
                    entry['bucket_bounds'] = list(fam.buckets)
                metrics.append(entry)
            from . import wire as _wire
            return {'process_index': self.process_index(),
                    'process_uid': _wire.process_uid(),
                    'metrics': metrics}

    def reset(self):
        """Zero every value (families and children survive) — opens a
        clean measurement window without re-plumbing instrument sites."""
        with self._lock:
            for fam in self._families.values():
                for child in fam._children.values():
                    if fam.type == 'histogram':
                        child.bucket_counts = [0] * len(child.bucket_counts)
                        child.sum = 0.0
                        child.count = 0
                        child._window.clear()
                    else:
                        child.value = 0.0

    # exporters live in observability.exporters; bound here for ergonomics
    def to_prometheus_text(self) -> str:
        from .exporters import to_prometheus_text
        return to_prometheus_text(self)

    def to_jsonl(self, path: Optional[str] = None) -> str:
        from .exporters import to_jsonl
        return to_jsonl(self, path)


def _cumulate(bucket_counts: List[int]) -> List[int]:
    out, acc = [], 0
    for c in bucket_counts:
        acc += c
        out.append(acc)
    return out


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def count_suppressed(site: str, registry: Optional[MetricsRegistry] = None):
    """Record an intentionally-swallowed error at a best-effort site into
    `paddle_suppressed_errors_total{site}`. This is the static-analysis
    contract for broad except blocks (the swallowed-exception pass): an
    error may be survivable, but it must never be *invisible* — a
    fallback that silently fires on every call shows up here instead of
    in a profile three weeks later. Never raises."""
    try:
        if not enabled():
            return
        reg = registry if registry is not None else _default_registry
        reg.counter(
            'paddle_suppressed_errors_total',
            'errors intentionally swallowed at best-effort sites '
            '(fallback taken); site names the swallow location',
            ('site',)).labels(site=site).inc()
    except Exception:  # paddle-lint: disable=swallowed-exception -- the error sink itself must never throw
        pass


def merge_snapshots(snapshots: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-host registry snapshots into one fleet view.

    Snapshots are deduped by process identity first — the
    `(process_uid, process_index)` pair. all_gather_object on a
    single-controller mesh returns world-size copies of the one local
    snapshot (same uid AND index — merging those must not multiply
    counters), while the fleet wire plane ships snapshots from distinct
    processes that may share a process_index but never a uid. Counters
    and histogram sums/counts add across hosts; gauges take the max
    (the fleet-wide watermark reading). The merged view lists the
    surviving `processes` (indexes) and `process_uids`.
    """
    by_proc: Dict[Any, Dict[str, Any]] = {}
    for s in snapshots:
        # (uid, index) pair: gathered copies of one snapshot share both
        # and collapse; distinct processes differ in uid even when their
        # process_index collides; snapshots taken from several
        # registries inside one process differ in index.
        key = (s.get('process_uid'), int(s.get('process_index', 0)))
        by_proc.setdefault(key, s)
    merged: Dict[str, Dict[str, Any]] = {}
    for snap in by_proc.values():
        for m in snap.get('metrics', []):
            tgt = merged.setdefault(m['name'], {
                'name': m['name'], 'type': m['type'], 'help': m['help'],
                'samples': {}})
            for s in m['samples']:
                key = tuple(sorted(s['labels'].items()))
                cur = tgt['samples'].get(key)
                if cur is None:
                    tgt['samples'][key] = {k: (dict(v) if isinstance(v, dict)
                                               else v)
                                           for k, v in s.items()}
                elif m['type'] == 'counter':
                    cur['value'] += s['value']
                elif m['type'] == 'gauge':
                    cur['value'] = max(cur['value'], s['value'])
                else:
                    cur['sum'] += s['sum']
                    cur['count'] += s['count']
                    for b, c in s['buckets'].items():
                        cur['buckets'][b] = cur['buckets'].get(b, 0) + c
                    # windowed quantiles can't be re-sketched from two
                    # windows; report the fleet-wide WORST per quantile
                    for q, v in (s.get('quantiles') or {}).items():
                        qd = cur.setdefault('quantiles', {})
                        qd[q] = max(qd.get(q, v), v)
    _recompute_goodput_fractions(merged)
    return {'processes': sorted({idx for _, idx in by_proc}),
            'process_uids': sorted({uid for uid, _ in by_proc
                                    if uid is not None}),
            'metrics': [{**m, 'samples': list(m['samples'].values())}
                        for m in merged.values()]}


def _recompute_goodput_fractions(merged: Dict[str, Dict[str, Any]]):
    """Goodput fractions are ratios, so the gauge-max merge rule is
    wrong for them: after counters merge (per-category seconds and wall
    seconds SUM across hosts), recompute every
    `paddle_goodput_fraction{category}` as merged-seconds / merged-wall
    — no double count, fractions still sum to ~1 fleet-wide."""
    secs = merged.get('paddle_goodput_seconds_total')
    wall_fam = merged.get('paddle_goodput_wall_seconds_total')
    frac = merged.get('paddle_goodput_fraction')
    if not (secs and wall_fam and frac):
        return
    wall = sum(s['value'] for s in wall_fam['samples'].values())
    if wall <= 0:
        return
    by_cat = {dict(key).get('category'): s['value']
              for key, s in secs['samples'].items()}
    for key, s in frac['samples'].items():
        cat = dict(key).get('category')
        if cat in by_cat:
            s['value'] = by_cat[cat] / wall
