"""Versioned JSONL wire format for the fleet observability plane.

Everything the in-process observability layer knows — registry metrics,
event-log entries, spans — stays blind outside its own interpreter:
`fleet_utils.gather_registry` merges snapshots over in-process XLA
collectives, which a separate router/replica *process* never joins.
This module is the process-boundary contract that fixes that, following
Monarch's push-based delta shipping (Adams et al., VLDB 2020) and
Dapper's cross-process trace model (Sigelman et al., 2010):

- A **segment** is one shippable unit: a header line followed by JSONL
  payload records. The header carries `(process_uid, seq, wall_ts,
  mono_ts)` — `seq` is the per-process monotone segment counter the
  aggregator dedupes on (re-shipping is idempotent), and the
  `(wall_ts, mono_ts)` pair (sampled at the same instant on the
  shipping process) is what lets the aggregator estimate per-process
  clock skew and project span timestamps onto one fleet timeline.
- **Metric payloads are deltas, not snapshots**: counters ship the
  monotonic increment since the last segment (order-independent under
  summation), gauges ship last-write values that the aggregator orders
  by segment seq (so out-of-order application converges), and
  histograms ship bucket/sum/count increments. The merge rules are the
  SAME ones `gather_registry`/`merge_snapshots` already applies
  in-process — `merge_states` literally delegates to
  `metrics.merge_snapshots`, so one rule set governs both planes.
- **Files are committed with the WeightStore's discipline**: payload
  written to a `.tmp` path, sha256 of the payload bytes recorded in
  the header (the per-segment manifest), then atomically renamed into
  the spool. A killed shipper leaves only an unreadable `.tmp` the
  aggregator never looks at; a torn/rotted committed file fails its
  sha256 on decode and is quarantined, never applied.

Wire records are plain JSON — no pickles, no framework types — so any
process that can write JSON lines to the spool directory participates
in the fleet view.
"""
from __future__ import annotations

import hashlib
import json
import os
import socket
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

WIRE_VERSION = 1

KIND_METRICS = 'metrics'
KIND_EVENTS = 'events'
KIND_SPANS = 'spans'
#: finalized per-request latency-ledger records (reqledger waterfalls):
#: the aggregator merges them per process so `/requests` and
#: `stitch_trace` phase annotations work fleet-wide
KIND_REQUESTS = 'requests'
KINDS = frozenset((KIND_METRICS, KIND_EVENTS, KIND_SPANS,
                   KIND_REQUESTS))

#: committed segment files (everything else in a spool dir is ignored)
SEGMENT_SUFFIX = '.jsonl'
#: suffix a quarantined segment is renamed to (kept for forensics)
QUARANTINE_SUFFIX = '.quarantined'


class WireError(ValueError):
    """A segment that must not be applied: unknown version, malformed
    JSON, or a payload that fails its sha256 manifest (torn write or
    bit rot). Aggregators quarantine on it — never crash, never apply."""


_process_uid: List[Optional[str]] = [None]


def process_uid() -> str:
    """Stable identity of THIS process on the fleet timeline:
    host-pid-nonce. The nonce makes pid reuse harmless (a recycled pid
    on the same host must not inherit a dead process's seq space)."""
    if _process_uid[0] is None:
        _process_uid[0] = (f'{socket.gethostname()}-{os.getpid()}-'
                           f'{uuid.uuid4().hex[:8]}')
    return _process_uid[0]


# ---------------------------------------------------------------------------
# metric deltas
# ---------------------------------------------------------------------------

def _sample_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _index_samples(snapshot_metric: Dict[str, Any]) -> Dict[Tuple, Dict]:
    return {_sample_key(s['labels']): s
            for s in snapshot_metric.get('samples', [])}


def metrics_delta(prev: Optional[Dict[str, Any]], cur: Dict[str, Any]
                  ) -> List[Dict[str, Any]]:
    """Delta records between two `MetricsRegistry.snapshot()` docs.
    `prev=None` means "first ship": every current value IS the delta.
    Families/samples with a zero delta are omitted — steady state ships
    nothing."""
    prev_by_name = {m['name']: m for m in (prev or {}).get('metrics', [])}
    out: List[Dict[str, Any]] = []
    for m in cur.get('metrics', []):
        pm = prev_by_name.get(m['name'])
        prev_samples = _index_samples(pm) if pm is not None else {}
        rec = {'name': m['name'], 'type': m['type'], 'help': m['help']}
        if m['type'] == 'histogram':
            rec['bucket_bounds'] = list(m.get('bucket_bounds', []))
        samples = []
        for s in m.get('samples', []):
            ps = prev_samples.get(_sample_key(s['labels']))
            if m['type'] == 'counter':
                d = s['value'] - (ps['value'] if ps else 0.0)
                if d != 0.0:
                    samples.append({'labels': s['labels'], 'delta': d})
            elif m['type'] == 'gauge':
                if ps is None or ps['value'] != s['value']:
                    samples.append({'labels': s['labels'],
                                    'value': s['value']})
            else:   # histogram
                cd = s['count'] - (ps['count'] if ps else 0)
                if cd == 0:
                    continue
                pb = (ps or {}).get('buckets', {})
                samples.append({
                    'labels': s['labels'],
                    'sum_delta': s['sum'] - (ps['sum'] if ps else 0.0),
                    'count_delta': cd,
                    'bucket_deltas': {b: c - pb.get(b, 0)
                                      for b, c in s['buckets'].items()
                                      if c - pb.get(b, 0) != 0},
                    'quantiles': dict(s.get('quantiles') or {}),
                })
        if samples:
            rec['samples'] = samples
            out.append(rec)
    return out


def new_state(uid: str, process_index: int = 0) -> Dict[str, Any]:
    """Empty per-process accumulation state for `fold_metrics_delta`."""
    return {'process_uid': uid, 'process_index': int(process_index),
            'families': {}}


def fold_metrics_delta(state: Dict[str, Any],
                       records: Sequence[Dict[str, Any]], seq: int):
    """Apply one metrics-delta payload into `state`. Safe under
    out-of-order and repeated-distinct-seq application: counter and
    histogram increments commute, and gauges/quantiles are last-write
    ordered by the shipping segment's `seq` (the larger seq wins no
    matter the arrival order). Idempotence for the SAME seq is the
    aggregator's job (it dedupes before folding)."""
    fams = state['families']
    for rec in records:
        fam = fams.get(rec['name'])
        if fam is None:
            fam = fams[rec['name']] = {
                'type': rec['type'], 'help': rec['help'], 'samples': {}}
            if rec['type'] == 'histogram':
                fam['bucket_bounds'] = list(rec.get('bucket_bounds', []))
        for s in rec.get('samples', []):
            key = _sample_key(s['labels'])
            cur = fam['samples'].get(key)
            if rec['type'] == 'counter':
                if cur is None:
                    cur = fam['samples'][key] = {'labels': dict(s['labels']),
                                                 'value': 0.0}
                cur['value'] += s['delta']
            elif rec['type'] == 'gauge':
                if cur is None or seq >= cur['seq']:
                    fam['samples'][key] = {'labels': dict(s['labels']),
                                           'value': s['value'], 'seq': seq}
            else:
                if cur is None:
                    cur = fam['samples'][key] = {
                        'labels': dict(s['labels']), 'sum': 0.0,
                        'count': 0, 'buckets': {}, 'quantiles': {},
                        'q_seq': -1}
                cur['sum'] += s['sum_delta']
                cur['count'] += s['count_delta']
                for b, c in s['bucket_deltas'].items():
                    cur['buckets'][b] = cur['buckets'].get(b, 0) + c
                if seq >= cur['q_seq']:
                    cur['quantiles'] = dict(s.get('quantiles') or {})
                    cur['q_seq'] = seq


def state_to_snapshot(state: Dict[str, Any]) -> Dict[str, Any]:
    """Render an accumulation state as a `snapshot()`-shaped doc so the
    in-process merge rules (`metrics.merge_snapshots`) apply verbatim."""
    metrics = []
    for name, fam in state['families'].items():
        samples = []
        for s in fam['samples'].values():
            if fam['type'] == 'histogram':
                samples.append({'labels': s['labels'], 'sum': s['sum'],
                                'count': s['count'],
                                'buckets': dict(s['buckets']),
                                'quantiles': dict(s['quantiles'])})
            else:
                samples.append({'labels': s['labels'], 'value': s['value']})
        entry = {'name': name, 'type': fam['type'], 'help': fam['help'],
                 'samples': samples}
        if fam['type'] == 'histogram':
            entry['bucket_bounds'] = list(fam.get('bucket_bounds', []))
        metrics.append(entry)
    return {'process_index': state['process_index'],
            'process_uid': state['process_uid'], 'metrics': metrics}


def merge_states(states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """One fleet view from per-process accumulation states — counters
    sum, gauges max, histograms add, goodput fractions recomputed: the
    SAME rules `fleet_utils.gather_registry` applies in-process,
    because this IS `metrics.merge_snapshots` (deduped by process_uid)."""
    from .metrics import merge_snapshots
    return merge_snapshots([state_to_snapshot(s) for s in states])


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------

def _payload_text(records: Sequence[Dict[str, Any]]) -> str:
    return ''.join(json.dumps(r, sort_keys=True) + '\n' for r in records)


def make_segment(kind: str, records: Sequence[Dict[str, Any]], seq: int,
                 uid: Optional[str] = None,
                 wall_ts: Optional[float] = None,
                 mono_ts: Optional[float] = None) -> Dict[str, Any]:
    """Build one segment dict: header + payload records. `wall_ts`
    (time.time) and `mono_ts` (the process's span clock, `events._now`)
    must be sampled at the same instant — the pair is the aggregator's
    skew-estimation input."""
    if kind not in KINDS:
        raise ValueError(f'unknown segment kind {kind!r}; want one of '
                         f'{sorted(KINDS)}')
    if mono_ts is None:
        from .events import _now
        mono_ts = _now()
    payload = _payload_text(records)
    return {
        'v': WIRE_VERSION,
        'kind': kind,
        'process_uid': uid if uid is not None else process_uid(),
        'seq': int(seq),
        'wall_ts': time.time() if wall_ts is None else float(wall_ts),
        'mono_ts': float(mono_ts),
        'n': len(records),
        'sha256': hashlib.sha256(payload.encode('utf-8')).hexdigest(),
        'records': list(records),
    }


def encode_segment(seg: Dict[str, Any]) -> str:
    header = {k: seg[k] for k in ('v', 'kind', 'process_uid', 'seq',
                                  'wall_ts', 'mono_ts', 'n', 'sha256')}
    return json.dumps(header, sort_keys=True) + '\n' \
        + _payload_text(seg['records'])


def decode_segment(text: str) -> Dict[str, Any]:
    """Parse + verify one encoded segment. Raises `WireError` on any
    reason not to apply it (version, malformed lines, sha mismatch,
    record-count mismatch) — the quarantine signal."""
    head, sep, payload = text.partition('\n')
    if not sep:
        raise WireError('segment has no payload separator')
    try:
        header = json.loads(head)
    except ValueError as e:
        raise WireError(f'unparseable segment header: {e}') from e
    if header.get('v') != WIRE_VERSION:
        raise WireError(f'wire version {header.get("v")!r} != '
                        f'{WIRE_VERSION}')
    if header.get('kind') not in KINDS:
        raise WireError(f'unknown segment kind {header.get("kind")!r}')
    digest = hashlib.sha256(payload.encode('utf-8')).hexdigest()
    if digest != header.get('sha256'):
        raise WireError(
            f'payload sha256 mismatch (manifest {header.get("sha256")!r}, '
            f'actual {digest!r}): torn write or bit rot')
    try:
        records = [json.loads(line) for line in payload.splitlines()
                   if line.strip()]
    except ValueError as e:
        raise WireError(f'unparseable payload record: {e}') from e
    if len(records) != int(header.get('n', -1)):
        raise WireError(f'record count {len(records)} != declared '
                        f'{header.get("n")!r}')
    header['records'] = records
    return header


def segment_filename(seg: Dict[str, Any]) -> str:
    return f'seg_{seg["seq"]:08d}_{seg["kind"]}{SEGMENT_SUFFIX}'


def write_segment(spool_dir: str, seg: Dict[str, Any]) -> str:
    """Commit one segment into `spool_dir/<process_uid>/` with the
    WeightStore discipline: tmp-write then atomic rename, so a reader
    never observes a half-written committed file, and a killed writer
    leaves only a `.tmp` nothing tails. Returns the committed path.
    Re-writing the same (uid, seq) is an atomic overwrite — idempotent
    by construction on the reader side (dedupe by (uid, seq))."""
    d = os.path.join(spool_dir, seg['process_uid'])
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, segment_filename(seg))
    tmp = f'{path}.{os.getpid()}.tmp'
    with open(tmp, 'w') as f:
        f.write(encode_segment(seg))
    os.replace(tmp, path)
    return path


def read_segment(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return decode_segment(f.read())
