"""paddle_tpu.observability — unified metrics + tracing layer.

One process-wide `MetricsRegistry` (labeled Counter/Gauge/Histogram),
one bounded `EventLog` of real-timestamped spans/events, and three
exporters (Prometheus text, JSONL, chrome-trace). Every subsystem
reports here — eager dispatch cache (via a scrape-time collector), jit
compiles (jax.monitoring listeners), eager collectives (per-axis
call/byte counters), optimizer host-offload (H2D/D2H bytes), and hapi
train loops (StepTelemetry) — so `debug.observability_summary()` or a
single export answers "where did this step's time, bytes, and compiles
go". Upstream Paddle scatters these across paddle.profiler,
FLAGS_check_nan_inf, and per-worker fleet logs; MegaScale
(arXiv:2402.15627) is the reference for why one substrate matters at
pod scale.

Multi-host: every exported sample is tagged with the host's
process_index; `distributed.fleet_utils.gather_registry()` merges
per-host snapshots over the existing collectives.

Cross-PROCESS (the fleet plane): `wire` is the versioned JSONL segment
format, `Shipper` spools a process's metric deltas / events / spans to
a shared directory, `Aggregator` tails spools into one merged view and
stitches skew-corrected cross-process traces, and `SLOEngine` judges
declarative objectives over the fleet view with multi-window burn-rate
alerting (breaches trigger flight-recorder bundles). The server gains
`/fleet/metrics`, `/fleet/trace`, and `/slo`.
"""
from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      DEFAULT_BUCKETS, QUANTILES, SlidingWindow,
                      count_suppressed, enable, enabled, disable,
                      get_registry, merge_snapshots)
from .events import (EVENT_SCHEMA, EventLog, Span, declare_event, emit,
                     get_event_log, span)
from .exporters import (chrome_track_metadata, fleet_to_prometheus_text,
                        read_jsonl, to_chrome_trace, to_jsonl,
                        to_prometheus_text)
from .wire import (WIRE_VERSION, WireError, decode_segment,
                   encode_segment, make_segment, metrics_delta,
                   process_uid, read_segment, write_segment)
from .shipper import Shipper
from .aggregator import (Aggregator, FleetSignalSource, get_aggregator,
                         set_aggregator)
from .slo import (Objective, SLOEngine, default_objectives,
                  get_engine as get_slo_engine,
                  set_engine as set_slo_engine)
from .telemetry import (StepTelemetry, collective_totals,
                        device_memory_bytes, install,
                        note_jit_cache_entry)
from .cost import (CatalogedJit, MfuWindow, ProgramCatalog, ProgramRecord,
                   aggregate_mfu, device_peaks, record_roofline,
                   roofline_summary, get_catalog as program_catalog)
from .goodput import (CATEGORIES as GOODPUT_CATEGORIES, GoodputLedger,
                      get_ledger)
from .reqledger import (BLOCKED_REASONS, PHASES as REQUEST_PHASES,
                        RequestLedger, RequestRecord,
                        get_ledger as get_request_ledger)
from .flight import FlightRecorder, get_flight_recorder
from .server import (ObservabilityServer, clear_degraded, degraded_states,
                     hang_suspected, health, note_degraded, note_progress,
                     note_weight_version, start_server, weight_versions)
from . import cost as _cost
from . import flight as _flight
from . import goodput as _goodput
from . import reqledger as _reqledger

__all__ = [
    'Counter', 'Gauge', 'Histogram', 'MetricsRegistry', 'DEFAULT_BUCKETS',
    'QUANTILES', 'SlidingWindow',
    'enable', 'enabled', 'disable', 'get_registry', 'merge_snapshots',
    'EVENT_SCHEMA', 'EventLog', 'Span', 'declare_event', 'emit',
    'get_event_log', 'span',
    'read_jsonl', 'to_chrome_trace', 'to_jsonl', 'to_prometheus_text',
    'chrome_track_metadata', 'fleet_to_prometheus_text',
    'WIRE_VERSION', 'WireError', 'decode_segment', 'encode_segment',
    'make_segment', 'metrics_delta', 'process_uid', 'read_segment',
    'write_segment',
    'Shipper', 'Aggregator', 'FleetSignalSource', 'get_aggregator',
    'set_aggregator',
    'Objective', 'SLOEngine', 'default_objectives', 'get_slo_engine',
    'set_slo_engine',
    'StepTelemetry', 'collective_totals', 'device_memory_bytes',
    'install', 'note_jit_cache_entry',
    'CatalogedJit', 'MfuWindow', 'ProgramCatalog', 'ProgramRecord',
    'program_catalog',
    'aggregate_mfu', 'device_peaks', 'record_roofline', 'roofline_summary',
    'GOODPUT_CATEGORIES', 'GoodputLedger', 'get_ledger',
    'BLOCKED_REASONS', 'REQUEST_PHASES', 'RequestLedger',
    'RequestRecord', 'get_request_ledger',
    'FlightRecorder', 'get_flight_recorder',
    'ObservabilityServer', 'clear_degraded', 'degraded_states',
    'hang_suspected', 'health', 'note_degraded', 'note_progress',
    'note_weight_version', 'start_server', 'weight_versions',
]

# register the jax.monitoring listeners + dispatch collector once at
# import; all hooks are no-ops while observability is disabled
install()
# program-catalog collector (paddle_program_* mirror), the always-on
# flight recorder's anomaly listener, and the always-on goodput ledger
# on the default event log
_cost.install()
_flight.install()
_goodput.install()
_reqledger.install()
