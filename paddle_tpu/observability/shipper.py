"""Shipper: spool this process's telemetry for a fleet aggregator.

The push half of the fleet observability plane (Monarch-style): a
background thread periodically snapshots the registry, computes the
wire-format delta since the last ship, collects the event-log tail, and
commits the result into a spool directory as sha256-manifested segments
(tmp-write + atomic rename — the WeightStore's stale-writer-safe
filesystem discipline, see `wire.write_segment`). A shared filesystem
IS the transport, exactly like the weight plane: no sockets, no serdes
beyond JSON, and any process that can mount the spool participates.

Design points:

- **Deltas, so shipping is idempotent and cheap.** Counters ship
  increments, gauges last-writes, histograms bucket increments; a
  quiet process ships nothing. The aggregator dedupes on
  `(process_uid, seq)`, so a re-ship (crash between write and
  bookkeeping, an operator re-running a spool sync) changes no merged
  counter.
- **The hot path never sees the shipper.** Instrument sites write to
  the in-process registry/event log as before; the shipper reads them
  at its own cadence on its own daemon thread, under the sanitized
  locks from the concurrency sanitizer (`ship_now` holds the shipper
  lock, the registry lock only nests inside it).
- **The event ring can outrun the shipper** — that loss is itself
  shipped: `EventLog.dropped` rides the registry as
  `paddle_events_dropped_total`, so the fleet view shows every
  process's drop count (the aggregator surfaces it per process).

`ship_now()` is the synchronous core (tests and final flush);
`start()`/`stop()` run it on an interval. `stop(flush=True)` ships the
tail so a graceful shutdown loses nothing.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import metrics as _metrics
from . import wire
from ..analysis.runtime import concurrency as _concurrency


class Shipper:
    """Spools registry deltas + event/span segments for one process.

    Args:
        spool_dir: shared spool root (the aggregator tails it); this
            process writes under `spool_dir/<process_uid>/`.
        registry: source MetricsRegistry (default: the process one).
        event_log: source EventLog (default: the process one).
        interval_s: background ship cadence.
        uid: override the process identity (tests simulating a fleet
            from one process).
    """

    def __init__(self, spool_dir: str, registry=None, event_log=None,
                 interval_s: float = 1.0, uid: Optional[str] = None):
        from .events import get_event_log
        self.spool_dir = spool_dir
        # `is None`, not truthiness: an empty registry/log is falsy
        self._registry = _metrics.get_registry() if registry is None \
            else registry
        self._log = get_event_log() if event_log is None else event_log
        self.interval_s = float(interval_s)
        self.uid = uid if uid is not None else wire.process_uid()
        self._lock = _concurrency.Lock('Shipper._lock')
        self._seq = 0
        self._prev_snapshot: Optional[Dict[str, Any]] = None
        self._last_event_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._shipped_segments = 0
        reg = _metrics.get_registry()
        self._m_segments = reg.counter(
            'paddle_segments_shipped_total',
            'fleet-plane segments committed to the spool', ('kind',))
        self._m_bytes = reg.counter(
            'paddle_segment_bytes_shipped_total',
            'encoded bytes committed to the fleet spool')

    # ------------------------------------------------------------------
    # the synchronous core
    # ------------------------------------------------------------------
    def ship_now(self) -> List[str]:
        """Build + commit the pending segments; returns committed paths
        (empty when nothing changed). One registry snapshot and one
        event-log copy per call — never per event."""
        with self._lock:
            snap = self._registry.snapshot()
            delta = wire.metrics_delta(self._prev_snapshot, snap)
            events = [e for e in self._log.events()
                      if e.get('seq', 0) > self._last_event_seq]
            spans = [e for e in events if e.get('ph') == 'X']
            instants = [e for e in events if e.get('ph') != 'X']
            # the same instant stamps wall and mono: the skew-estimation
            # pair every segment of this batch carries
            from .events import _now
            from .reqledger import get_ledger as _get_reqledger
            wall_ts, mono_ts = time.time(), _now()
            # finalized request waterfalls ship as their own kind; their
            # 'ts' fields ride the span clock, so the aggregator's skew
            # offsets project them onto the fleet timeline unchanged
            requests = _get_reqledger().drain_wire_records()
            paths: List[str] = []
            total_bytes = 0
            for kind, records in ((wire.KIND_METRICS, delta),
                                  (wire.KIND_EVENTS, instants),
                                  (wire.KIND_SPANS, spans),
                                  (wire.KIND_REQUESTS, requests)):
                if not records:
                    continue
                self._seq += 1
                seg = wire.make_segment(kind, records, self._seq,
                                        uid=self.uid, wall_ts=wall_ts,
                                        mono_ts=mono_ts)
                paths.append(wire.write_segment(self.spool_dir, seg))
                total_bytes += len(wire.encode_segment(seg))
                if _metrics.enabled():
                    self._m_segments.labels(kind=kind).inc()
            self._prev_snapshot = snap
            if events:
                self._last_event_seq = max(e.get('seq', 0) for e in events)
            self._shipped_segments += len(paths)
            if total_bytes and _metrics.enabled():
                self._m_bytes.inc(total_bytes)
        if paths:
            from .events import emit
            emit('segment_shipped', n=len(paths), seq=self._seq,
                 process_uid=self.uid)
        return paths

    # ------------------------------------------------------------------
    # background thread
    # ------------------------------------------------------------------
    def start(self) -> 'Shipper':
        """Ship on `interval_s` from a daemon thread. Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f'paddle-shipper:{self.uid}',
            daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.ship_now()
            except Exception:
                # a broken ship must not kill the thread (the spool disk
                # filling up is an ops problem, not a process-fatal one)
                # — but it must be countable
                _metrics.count_suppressed('shipper.ship')

    def stop(self, flush: bool = True):
        """Stop the background thread; `flush` ships the tail first so
        graceful shutdown loses no telemetry."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None
        if flush:
            try:
                self.ship_now()
            except Exception:
                _metrics.count_suppressed('shipper.flush')

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {'process_uid': self.uid, 'seq': self._seq,
                    'segments_shipped': self._shipped_segments,
                    'last_event_seq': self._last_event_seq,
                    'running': self._thread is not None
                    and self._thread.is_alive()}
