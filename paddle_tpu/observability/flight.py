"""Always-on flight recorder: the anomalous window is always on disk.

When the resilience layer flags a hang, a loss spike, an exhausted skip
budget, or the serving engine fails a request, the evidence — the spans
around the bad step, the loss trajectory into it, the device-memory
curve, which compiled programs were running — is usually gone by the
time anyone attaches a debugger. Production practice (PaLM's
continuous monitoring of long runs; every aircraft) is to record
continuously into a bounded ring and dump the ring WHEN the anomaly
fires, so every incident ships its own postmortem bundle.

The recorder rides the instrumentation that already exists: per-step
samples arrive from `StepTelemetry.step` (loss, tokens/sec, memory
watermark), spans/events live in the shared `EventLog`, and the
trigger is an `EventLog` listener watching for the anomaly events the
runtime already emits (`hang_suspected`, `loss_spike`, `bad_step`,
`skip_budget_exhausted`, `serving_request_failed`). A dump bundles:

  flight.json    trigger + ring of step/memory samples + metric deltas
  events.jsonl   the event-log tail (spans around the anomaly)
  trace.json     the same window as a chrome trace
  metrics.json   full registry snapshot
  programs.json  ProgramCatalog snapshot (per-program cost attribution)
  goodput.json   goodput-ledger books + roofline/MFU attribution
  prefix_cache.json  serving radix-prefix-cache state (when serving)
  slo.json       SLO burn-rate state + per-process event-drop counts
  summary.txt    debug.observability_summary()

Auto-dumps are debounced (`min_interval_s`) so an anomaly storm
produces one bundle per window, not thousands; manual `dump()` always
writes.
"""
from __future__ import annotations

import collections
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from . import metrics as _metrics
from ..analysis.runtime import concurrency as _concurrency

# anomaly events that auto-trigger a dump (emitted by resilience/
# serving/debug — see each site)
TRIGGER_EVENTS = frozenset((
    'hang_suspected', 'loss_spike', 'bad_step', 'skip_budget_exhausted',
    'serving_request_failed', 'checkpoint_corrupt',
    'router_failover_storm', 'donation_quarantined',
    'sanitizer_violation', 'slo_breach', 'segment_quarantined',
    'replica_crash', 'replica_quarantined', 'request_slow',
))


def _default_dir() -> str:
    return os.environ.get(
        'PADDLE_FLIGHT_DIR',
        os.path.join(tempfile.gettempdir(),
                     f'paddle_flight_{os.getpid()}'))


class FlightRecorder:
    """Bounded ring of recent step/memory samples + anomaly-triggered
    postmortem dumps. Always on: recording is a deque append per step.

    The rings are written by the training/serving thread and read by
    whatever thread EMITTED the trigger event (a watchdog or scrape
    thread dumping mid-run) — iterating a deque while another thread
    appends raises "deque mutated during iteration", which is exactly
    the postmortem dying mid-incident. Both rings are declared
    `guarded_by('_lock')` so the concurrency sanitizer enforces the
    discipline the hard-won fix below established: every access copies
    or appends under the lock."""

    _steps = _concurrency.guarded_by('_lock', mutable=True)
    _memory = _concurrency.guarded_by('_lock', mutable=True)

    def __init__(self, capacity: int = 512,
                 min_interval_s: float = 60.0,
                 dump_dir: Optional[str] = None):
        self.capacity = int(capacity)
        self.min_interval_s = float(min_interval_s)
        self.dump_dir = dump_dir or _default_dir()
        self._lock = _concurrency.Lock('FlightRecorder._lock')
        self._steps: collections.deque = collections.deque(maxlen=capacity)
        self._memory: collections.deque = collections.deque(
            maxlen=capacity)
        self._last_dump_t: Optional[float] = None
        self._last_counters: Dict[str, float] = {}
        self._dumping = False
        self._n_dumps = 0
        self.dumps: List[str] = []

    # -- recording (hot-ish path: one locked deque append per step) ---------
    def record_step(self, loss=None, tokens_per_sec: Optional[float] = None,
                    step: Optional[int] = None):
        sample = {'t': time.time(), 'step': step}
        if loss is not None:
            sample['loss'] = float(loss)
        if tokens_per_sec is not None:
            sample['tokens_per_sec'] = float(tokens_per_sec)
        with self._lock:
            self._steps.append(sample)

    def record_memory(self, nbytes: int):
        with self._lock:
            self._memory.append({'t': time.time(), 'bytes': int(nbytes)})

    # -- triggering ---------------------------------------------------------
    def on_event(self, event: Dict[str, Any]):
        """EventLog listener: an anomaly event lands a debounced dump."""
        if event.get('name') not in TRIGGER_EVENTS:
            return
        now = time.monotonic()
        with self._lock:
            if self._dumping:
                return
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.min_interval_s):
                return
            self._last_dump_t = now
        try:
            self.dump(reason=event.get('name'), trigger=event)
        except Exception:
            # a failed postmortem must never kill the run — but a
            # recorder that silently stopped dumping is a postmortem
            # with no body; count it
            _metrics.count_suppressed('flight.dump')

    # -- the postmortem bundle ----------------------------------------------
    def _headline_counters(self, reg) -> Dict[str, float]:
        out = {}
        for name in ('paddle_steps_total', 'paddle_jit_compiles_total',
                     'paddle_resilience_rollbacks_total',
                     'paddle_resilience_hangs_total',
                     'paddle_serving_tokens_total',
                     'paddle_serving_decode_steps_total',
                     'paddle_program_cache_misses_total'):
            out[name] = reg.value(name)
        # program-store hit/reject counters are labeled (tier/reason):
        # the headline view wants the totals
        for name in ('paddle_program_cache_hits_total',
                     'paddle_program_cache_rejects_total'):
            fam = reg.get(name)
            out[name] = fam.total() if fam is not None else 0.0
        return out

    def dump(self, dir: Optional[str] = None, reason: str = 'manual',
             trigger: Optional[Dict[str, Any]] = None) -> str:
        """Write one postmortem bundle; returns its directory."""
        from .cost import get_catalog
        from .events import get_event_log
        from .exporters import to_chrome_trace
        with self._lock:
            self._dumping = True
            self._n_dumps += 1
            n = self._n_dumps
        try:
            base = dir or self.dump_dir
            stamp = time.strftime('%Y%m%d_%H%M%S')
            path = os.path.join(base, f'flight_{n:03d}_{stamp}_{reason}')
            os.makedirs(path, exist_ok=True)
            reg = _metrics.get_registry()
            log = get_event_log()

            counters = self._headline_counters(reg)
            deltas = {k: v - self._last_counters.get(k, 0.0)
                      for k, v in counters.items()}
            self._last_counters = counters
            with self._lock:
                # copy under the lock: the train/serving thread keeps
                # appending while this (listener) thread dumps — an
                # unlocked list() dies with "deque mutated during
                # iteration" exactly when the postmortem matters
                steps = list(self._steps)
                memory = list(self._memory)
            with open(os.path.join(path, 'flight.json'), 'w') as f:
                json.dump({
                    'reason': reason, 'trigger': trigger,
                    'time': time.time(),
                    'steps': steps,
                    'memory': memory,
                    'counters': counters,
                    'counters_delta_since_last_dump': deltas,
                }, f, indent=1, default=str)
            log.to_jsonl(os.path.join(path, 'events.jsonl'))
            to_chrome_trace(log, os.path.join(path, 'trace.json'))
            with open(os.path.join(path, 'metrics.json'), 'w') as f:
                json.dump(reg.snapshot(), f, indent=1)
            cat = get_catalog()
            programs_doc = cat.snapshot()
            try:
                from ..programs import get_store
                # cold-start posture rides every postmortem: was this
                # process serving warm-loaded or freshly-compiled code?
                programs_doc['store'] = get_store().stats()
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
            with open(os.path.join(path, 'programs.json'), 'w') as f:
                json.dump(programs_doc, f, indent=1, default=str)
            try:
                # where the seconds went INTO this incident: the ledger
                # + roofline books are the first thing a postmortem
                # reader wants next to the loss/memory rings
                from .cost import roofline_summary
                from .goodput import get_ledger
                with open(os.path.join(path, 'goodput.json'), 'w') as f:
                    json.dump({'goodput': get_ledger().report(),
                               'roofline': roofline_summary()},
                              f, indent=1, default=str)
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
                # partial bundle beats none mid-crash
            try:
                # per-request phase waterfalls: which requests were slow
                # at the moment of the incident and WHERE their
                # milliseconds went (the request_slow trigger's own
                # evidence section — the bundle answers "why" without a
                # live /requests endpoint)
                from .reqledger import get_ledger as _get_reqledger
                with open(os.path.join(path, 'requests.json'),
                          'w') as f:
                    json.dump(_get_reqledger().report(), f, indent=1,
                              default=str)
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
            try:
                # serving prefix-cache posture: what was retained /
                # pinned when the anomaly fired (an eviction storm or a
                # pinned-full cache is a likely TTFT-regression cause)
                from ..serving.prefix_cache import snapshot_all
                caches = snapshot_all()
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
                caches = []
            if caches:
                with open(os.path.join(path, 'prefix_cache.json'),
                          'w') as f:
                    json.dump(caches, f, indent=1, default=str)
            try:
                # fleet/SLO posture: burn-rate state at the moment of
                # the incident plus per-process event-ring drop counts
                # (whose telemetry is truncated) — the breach bundle's
                # own evidence section
                from .aggregator import get_aggregator
                from .slo import get_engine
                slo_doc: Dict[str, Any] = {
                    'local_events_dropped': log.dropped}
                engine = get_engine()
                if engine is not None:
                    slo_doc['slo'] = engine.report()
                agg = get_aggregator()
                if agg is not None:
                    slo_doc['fleet_events_dropped'] = agg.events_dropped()
                    slo_doc['fleet_processes'] = agg.process_uids()
                    slo_doc['clock_offsets'] = agg.clock_offsets()
                with open(os.path.join(path, 'slo.json'), 'w') as f:
                    json.dump(slo_doc, f, indent=1, default=str)
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
            try:
                from .. import debug
                summary = debug.observability_summary() + '\n'
            except Exception:
                _metrics.count_suppressed('flight.bundle_section')
                summary = ''   # partial bundle beats none mid-crash
            with open(os.path.join(path, 'summary.txt'), 'w') as f:
                f.write(summary + cat.report() + '\n')
            self.dumps.append(path)
            if _metrics.enabled():
                reg.counter('paddle_flight_dumps_total',
                            'flight-recorder postmortem bundles written',
                            ('reason',)).labels(reason=reason).inc()
            return path
        finally:
            with self._lock:
                self._dumping = False

    def clear(self):
        with self._lock:
            self._steps.clear()
            self._memory.clear()


_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _recorder


def install():
    """Idempotent: hook the default EventLog so anomaly events trigger
    dumps (runs at package import — the recorder is always on)."""
    from .events import get_event_log
    get_event_log().add_listener(_recorder.on_event)
