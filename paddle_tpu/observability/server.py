"""HTTP observability endpoint: scrape a live trainer/server from outside.

PR 2's metrics/event core was a pull-from-Python library — nothing could
look at a running process without code on the inside. This is the
standard production answer: a stdlib `http.server` daemon thread (no new
dependencies) serving the shared registry and event log the way every
fleet scraper expects:

  /metrics   Prometheus text (the registry, collectors included)
  /healthz   process liveness: 200 while steps/decodes make progress,
             503 JSON while any armed watchdog suspects a hang
  /summary   debug.observability_summary() (?format=json for the dict)
  /events    JSONL tail of the event log (?n=200, bounded; ?type=a,b
             filters by event name, ?since=SEQ or ?since=TS.S resumes
             from a sequence number / timestamp cursor)
  /trace     chrome://tracing JSON of the event log
  /programs  ProgramCatalog report (?format=json for top_programs())
  /goodput   goodput-ledger report (?format=json for the dict)
  /fleet/metrics  Prometheus text of the FLEET view (merged + one
             labeled section per process) — requires a registered
             Aggregator (`aggregator.set_aggregator`), 503 otherwise
  /fleet/trace    skew-corrected cross-process chrome trace
             (?trace_id=N stitches one request's waterfall)
  /slo       SLO engine report: burn rates, budget remaining, breaches
             (requires `slo.set_engine`, 503 otherwise)

`start_server(port)` is wired into examples/train_gpt.py and
examples/serve_gpt.py via `--metrics-port`; port 0 binds an ephemeral
port (tests). Handlers only READ shared state under the registry lock,
so scrapes are safe concurrent with training/decoding threads.

This module also owns the process's *liveness* state: instrumented
loops call `note_progress(kind)` per step/decode round (StepTelemetry
and the serving engine do this), and the resilience watchdog flips
`note_hang` / `clear_hang` around a suspected hang — /healthz is the
external view of both.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..analysis.runtime import concurrency as _concurrency

# -- liveness state (written by instrumented loops + the watchdog) ----------
_live_lock = _concurrency.Lock('server._live_lock')
_progress: Dict[str, float] = {}        # kind -> monotonic ts of last beat
_hangs: Dict[int, Dict[str, Any]] = {}  # watchdog id -> hang info
# (scope, state) -> {'count': refs, 'info': context}. Ref-counted, NOT
# last-writer-wins: two independent reasons to be degraded (a replica
# draining WHILE the process re-meshes, two engines draining at once)
# each hold their own reference, and /healthz stays 503 until every
# holder clears. `scope` namespaces per-entity states (the serving
# router tags each replica's engine) so a fleet router can tell WHICH
# replica is degraded; scope None is the process itself.
_degraded: Dict[Tuple[Optional[str], str], Dict[str, Any]] = {}
# scope -> live weight version (None scope = the process/trainer view);
# serving engines report through note_weight_version on every hot swap
# so /healthz answers "which weights is this replica serving" without
# touching the engine
_weight_versions: Dict[Optional[str], int] = {}
_START = time.monotonic()


def note_progress(kind: str = 'step'):
    """Heartbeat: an instrumented loop completed one unit of `kind`
    ('step', 'decode', ...). Cheap enough to call every step."""
    with _live_lock:
        _progress[kind] = time.monotonic()


def note_hang(key: int, info: Optional[Dict[str, Any]] = None):
    """A watchdog suspects the step under `key` is hung; /healthz goes
    non-200 until `clear_hang(key)` (the step finally returning)."""
    with _live_lock:
        _hangs[key] = dict(info or {})


def clear_hang(key: int):
    with _live_lock:
        _hangs.pop(key, None)


def hang_suspected() -> bool:
    return bool(_hangs)


def note_degraded(state: str, info: Optional[Dict[str, Any]] = None,
                  scope: Optional[str] = None):
    """The process entered a degraded-but-alive phase — re-meshing after
    a topology change ('resizing'), draining before a preemption exit
    ('draining'). /healthz reports the state at 503 (so routers stop
    sending traffic / schedulers know not to kill a transitioning
    process) until every `note_degraded` is matched by a
    `clear_degraded`: each call takes one reference, so concurrent
    holders of the same state (two draining engines) keep the 503 up
    until BOTH clear. `scope` namespaces the state per entity (the
    serving router scopes each replica's engine as 'replica:N')."""
    with _live_lock:
        entry = _degraded.get((scope, state))
        if entry is None:
            entry = _degraded[(scope, state)] = {'count': 0, 'info': {}}
        entry['count'] += 1
        if info:
            entry['info'] = dict(info)


def clear_degraded(state: str, scope: Optional[str] = None,
                   force: bool = False):
    """Drop one reference on `state` (the pair to a `note_degraded`);
    the state leaves /healthz when the last holder clears. `force`
    removes it outright regardless of holders (test teardown)."""
    with _live_lock:
        entry = _degraded.get((scope, state))
        if entry is None:
            return
        entry['count'] -= 1
        if force or entry['count'] <= 0:
            del _degraded[(scope, state)]


def note_weight_version(version: int, scope: Optional[str] = None):
    """Record the weight version `scope` (a replica's engine, or the
    process itself when None) is currently serving/training; shows up
    in the /healthz payload as `weight_versions` so a mixed-version
    fleet is observable from the outside during a rolling swap."""
    with _live_lock:
        _weight_versions[scope] = int(version)


def weight_versions() -> Dict[str, int]:
    """Live weight versions by scope ('process' for the scope-None
    entry) — the /healthz `weight_versions` payload."""
    with _live_lock:
        return {(sc if sc is not None else 'process'): v
                for sc, v in sorted(_weight_versions.items(),
                                    key=lambda kv: kv[0] or '')}


def degraded_states(scope: Optional[str] = '*') -> Dict[str, Dict[str, Any]]:
    """Active degraded states: `scope='*'` merges every scope, `None`
    returns only process-global states, any other string returns that
    scope's states."""
    with _live_lock:
        out: Dict[str, Dict[str, Any]] = {}
        for (sc, state), entry in _degraded.items():
            if scope == '*' or sc == scope:
                out[state] = dict(entry['info'])
        return out


def health() -> Dict[str, Any]:
    """The /healthz body: liveness + watchdog state + degraded phases +
    seconds since the last step/decode heartbeat. `states` lists EVERY
    active degraded state (+hang) — a process that is simultaneously
    draining and hang-suspected shows both, and stays 503 until both
    clear."""
    import os
    now = time.monotonic()
    with _live_lock:
        since = {k: round(now - t, 3) for k, t in _progress.items()}
        hangs = [dict(v) for v in _hangs.values()]
        degraded = {}
        names = set()
        for (scope, state), entry in sorted(
                _degraded.items(), key=lambda kv: (kv[0][0] or '',
                                                   kv[0][1])):
            key = state if scope is None else f'{scope}/{state}'
            degraded[key] = dict(entry['info'])
            degraded[key]['count'] = entry['count']
            if scope is not None:
                degraded[key]['scope'] = scope
            names.add(state)
    if hangs:
        names.add('hang_suspected')
    states = sorted(names)
    status = ('hang_suspected' if hangs
              else '+'.join(states) if states else 'ok')
    return {
        'status': status,
        'pid': os.getpid(),
        'uptime_s': round(now - _START, 3),
        'seconds_since_progress': since,
        'hangs': hangs,
        'states': states,
        'degraded': degraded,
        'weight_versions': weight_versions(),
    }


# -- the endpoint ------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # one handler instance per request (ThreadingHTTPServer)
    protocol_version = 'HTTP/1.1'

    def log_message(self, fmt, *args):   # scrapes must not spam stdout
        pass

    def _send(self, body: str, content_type: str = 'text/plain',
              status: int = 200):
        data = body.encode('utf-8')
        self.send_response(status)
        self.send_header('Content-Type', f'{content_type}; charset=utf-8')
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _query(self) -> Dict[str, str]:
        return {k: v[-1] for k, v in
                parse_qs(urlparse(self.path).query).items()}

    def do_GET(self):
        route = urlparse(self.path).path.rstrip('/') or '/'
        try:
            handler = {
                '/': self._index, '/metrics': self._metrics,
                '/healthz': self._healthz, '/summary': self._summary,
                '/events': self._events, '/trace': self._trace,
                '/programs': self._programs, '/goodput': self._goodput,
                '/fleet/metrics': self._fleet_metrics,
                '/fleet/trace': self._fleet_trace, '/slo': self._slo,
                '/requests': self._requests,
            }.get(route)
            if handler is None:
                self._send(f'unknown route {route}\n', status=404)
            else:
                handler()
        except BrokenPipeError:
            pass   # scraper went away mid-response
        except Exception as exc:   # a broken section must not kill scraping
            self._send(f'{type(exc).__name__}: {exc}\n', status=500)

    def _index(self):
        self._send('paddle_tpu observability: /metrics /healthz /summary '
                   '/events /trace /programs /goodput /fleet/metrics '
                   '/fleet/trace /slo /requests\n')

    def _metrics(self):
        from .exporters import to_prometheus_text
        self._send(to_prometheus_text(),
                   content_type='text/plain; version=0.0.4')

    def _healthz(self):
        body = health()
        self._send(json.dumps(body, indent=1) + '\n',
                   content_type='application/json',
                   status=200 if body['status'] == 'ok' else 503)

    def _summary(self):
        from .. import debug
        if self._query().get('format') == 'json':
            self._send(json.dumps(debug.observability_summary(as_dict=True))
                       + '\n', content_type='application/json')
        else:
            self._send(debug.observability_summary() + '\n')

    # /events responses are bounded no matter what the client asks for:
    # a scraper passing n=10**9 (or a since= cursor matching the whole
    # ring) still gets at most this many lines
    EVENTS_MAX = 2000

    def _events(self):
        from .events import get_event_log
        q = self._query()
        try:
            n = int(q.get('n', 200))
        except ValueError:
            n = 200
        n = min(max(n, 0), self.EVENTS_MAX)
        events = get_event_log().events()
        since = q.get('since')
        if since:
            try:
                if '.' in since:
                    ts = float(since)   # timestamp on the span clock
                    events = [e for e in events if e.get('ts', 0.0) >= ts]
                else:
                    seq = int(since)    # sequence-number cursor
                    events = [e for e in events if e.get('seq', 0) > seq]
            except ValueError:
                self._send(f'bad since= cursor {since!r} '
                           f'(want SEQ or TS.S)\n', status=400)
                return
        types = q.get('type')
        if types:
            wanted = set(t for t in types.split(',') if t)
            events = [e for e in events if e.get('name') in wanted]
        trace_id = q.get('trace_id')
        if trace_id is not None:
            try:
                trace_id = int(trace_id)
            except ValueError:
                pass   # string trace ids pass through as-is
            # one request's events — the /requests drill-down (the same
            # request_id attr convention /fleet/trace stitches on)
            events = [e for e in events
                      if (e.get('attrs') or {}).get('request_id')
                      == trace_id]
        self._send(''.join(json.dumps(e) + '\n' for e in events[-n:]),
                   content_type='application/jsonl')

    def _trace(self):
        from .exporters import to_chrome_trace
        self._send(json.dumps(to_chrome_trace()),
                   content_type='application/json')

    def _programs(self):
        from .cost import get_catalog
        cat = get_catalog()
        if self._query().get('format') == 'json':
            self._send(json.dumps({'programs': cat.top_programs(n=50)})
                       + '\n', content_type='application/json')
        else:
            self._send(cat.report() + '\n')

    def _fleet_metrics(self):
        from .aggregator import get_aggregator
        from .exporters import fleet_to_prometheus_text
        agg = get_aggregator()
        if agg is None:
            self._send('no fleet aggregator registered (see '
                       'observability.aggregator.set_aggregator)\n',
                       status=503)
            return
        agg.poll()
        self._send(fleet_to_prometheus_text(agg),
                   content_type='text/plain; version=0.0.4')

    def _fleet_trace(self):
        from .aggregator import get_aggregator
        agg = get_aggregator()
        if agg is None:
            self._send('no fleet aggregator registered (see '
                       'observability.aggregator.set_aggregator)\n',
                       status=503)
            return
        agg.poll()
        trace_id = self._query().get('trace_id')
        if trace_id is not None:
            try:
                trace_id = int(trace_id)
            except ValueError:
                pass   # string trace ids pass through as-is
        self._send(json.dumps(agg.stitch_trace(trace_id=trace_id)),
                   content_type='application/json')

    def _slo(self):
        from .slo import get_engine
        engine = get_engine()
        if engine is None:
            self._send('no SLO engine registered (see '
                       'observability.slo.set_engine)\n', status=503)
            return
        if self._query().get('poll') == '1':
            engine.poll()
        self._send(json.dumps(engine.report(), indent=1, default=str)
                   + '\n', content_type='application/json')

    def _goodput(self):
        from .cost import roofline_summary
        from .goodput import get_ledger
        ledger = get_ledger()
        if self._query().get('format') == 'json':
            self._send(json.dumps({'goodput': ledger.report(),
                                   'roofline': roofline_summary()})
                       + '\n', content_type='application/json')
        else:
            self._send(ledger.report_text() + '\n')

    def _requests(self):
        """The per-request latency ledger: ?top=N caps the slowest-K
        waterfalls returned (default all retained); the payload carries
        the per-phase p50/p99 decomposition and the p99-driver ranking
        — 'where did my p99 go' as data. When a fleet aggregator is
        registered, its merged cross-process waterfalls ride along
        under 'fleet'."""
        from .reqledger import get_ledger
        q = self._query()
        top = None
        if q.get('top'):
            try:
                top = max(int(q['top']), 0)
            except ValueError:
                self._send(f'bad top= {q["top"]!r} (want an int)\n',
                           status=400)
                return
        body = get_ledger().report(top=top)
        from .aggregator import get_aggregator
        agg = get_aggregator()
        if agg is not None:
            agg.poll()
            fleet = agg.requests()
            body['fleet'] = fleet[-(top or len(fleet) or 1):]
        self._send(json.dumps(body, indent=1, default=str) + '\n',
                   content_type='application/json')


class ObservabilityServer:
    """A bound, running endpoint; `stop()` to shut down (daemon threads
    die with the process otherwise — safe for long trainers)."""

    def __init__(self, port: int = 0, host: str = '0.0.0.0'):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f'paddle-obs-server:{self.port}', daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        host = '127.0.0.1' if self.host == '0.0.0.0' else self.host
        return f'http://{host}:{self.port}'

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)

    def __repr__(self):
        return f'ObservabilityServer(url={self.url!r})'


_servers = []


def start_server(port: int = 0, host: str = '0.0.0.0'
                 ) -> ObservabilityServer:
    """Start the observability endpoint on a daemon thread; returns the
    running server (`.port` carries the bound port when port=0)."""
    srv = ObservabilityServer(port=port, host=host)
    _servers.append(srv)
    return srv
