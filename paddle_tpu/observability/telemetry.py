"""Runtime instrumentation: jit-compile listeners, dispatch collector,
device-memory watermark, and per-step training telemetry.

Wires the passive sources into the registry:
- `jax.monitoring` duration listeners turn every backend compile into
  `paddle_jit_compiles_total` / `paddle_jit_compile_seconds_total` —
  the host-side view of "where did my step go" that xprof's device
  traces assume the framework provides (upstream analogue: the
  to_static program-cache hit logs).
- a registry collector mirrors the eager dispatch cache's raw counters
  (paddle_tpu._dispatch) into `paddle_dispatch_*` metrics at snapshot
  time — zero per-op cost, `debug.dispatch_stats()` stays the raw view.
- `StepTelemetry` tracks steps/sec, tokens/sec, last loss, and the
  device-memory watermark (`memory_stats()` when the backend reports
  it, live-array bytes fallback on CPU); hapi's MetricsLoggerCallback
  and examples/train_gpt.py drive it per train step.
"""
from __future__ import annotations

import collections
import time
from typing import Optional

from . import metrics as _metrics

_installed = [False]


def _synthetic_span(name: str, secs: float):
    """Feed a completed host region straight into the goodput ledger.
    The duration listener fires at region END on the emitting thread,
    so begin = now - secs lands the interval on the span clock AND
    keeps the child-before-parent ordering the ledger's nested-span
    subtraction relies on (a compile inside a train step is credited
    before the step span ends). Direct call, NOT an event-log append —
    a busy dispatch cache compiles thousands of entries per session and
    would flush the bounded event ring."""
    from . import events as _events
    from .goodput import get_ledger
    get_ledger().note_span(name, _events._now() - secs, secs)


def _on_jax_duration(name: str, secs: float, **kw):
    if not _metrics.enabled():
        return
    reg = _metrics.get_registry()
    if name.endswith('backend_compile_duration'):
        reg.counter('paddle_jit_compiles_total',
                    'XLA backend compiles').inc()
        reg.counter('paddle_jit_compile_seconds_total',
                    'seconds spent in XLA backend compile').inc(secs)
        _synthetic_span('jit.compile', secs)
    elif name.endswith('jaxpr_trace_duration'):
        reg.counter('paddle_jit_trace_seconds_total',
                    'seconds spent tracing python to jaxpr').inc(secs)
        _synthetic_span('jit.trace', secs)


def _on_jax_event(name: str, **kw):
    """Instant-event listener: the persistent compilation cache emits
    `/jax/compilation_cache/cache_hits` when a backend "compile" was
    actually served from disk. `paddle_jit_compiles_total` ticks either
    way (the duration event wraps the whole compile-or-get-cached
    call), so REAL compiles in a window = compiles delta minus cache
    hits delta — the program store's zero-compile warm-restart guards
    assert that difference is zero."""
    if not _metrics.enabled():
        return
    if name.endswith('cache_hits'):
        _metrics.get_registry().counter(
            'paddle_jit_cache_hits_total',
            'XLA backend compiles served from the persistent '
            'compilation cache').inc()


def _dispatch_collector(reg: '_metrics.MetricsRegistry'):
    """Scrape-time mirror of the dispatch cache's raw counters."""
    from .. import _dispatch
    s = _dispatch.stats()
    calls = reg.counter('paddle_dispatch_calls_total',
                        'eager apply_op dispatches by result', ('result',))
    for key in ('hits', 'misses', 'retraces', 'fallbacks', 'errors'):
        c = calls.labels(result=key)
        c.value = float(s[key])   # mirror, not accumulate
    reg.gauge('paddle_dispatch_hit_rate',
              'dispatch cache hit rate').set(s['hit_rate'])
    reg.gauge('paddle_dispatch_cache_entries',
              'compiled entries resident in the dispatch cache').set(
                  s['cache_size'])
    ev = reg.counter('paddle_dispatch_evictions_total',
                     'dispatch-cache LRU evictions')
    ev._sole().value = float(s['evictions'])   # mirror, not accumulate


def install():
    """Idempotent: register the jax.monitoring listeners and the
    dispatch collector on the default registry. Runs at package import;
    safe to call again (e.g. after jax.monitoring.clear_event_listeners
    in a test)."""
    reg = _metrics.get_registry()
    reg.register_collector(_dispatch_collector)
    if _installed[0]:
        return
    try:
        from jax import monitoring as _mon
        _mon.register_event_duration_secs_listener(_on_jax_duration)
        _mon.register_event_listener(_on_jax_event)
        _installed[0] = True
    except Exception:  # paddle-lint: disable=swallowed-exception -- jax without monitoring hooks: compile metrics stay at zero, documented
        pass   # jax without monitoring: compile metrics stay at zero


def note_jit_cache_entry(kind: str = 'to_static'):
    """Called by jit.StaticLayer (and friends) when a new executable
    lands in a python-side jit cache."""
    if not _metrics.enabled():
        return
    _metrics.get_registry().gauge(
        'paddle_jit_cache_entries',
        'executables held by python-side jit caches', ('kind',)).labels(
            kind=kind).inc()


def collective_totals(reg: Optional['_metrics.MetricsRegistry'] = None
                      ) -> dict:
    """Sum the per-(op, axis) collective counters into totals plus a
    per-label breakdown: {'calls', 'bytes', 'per_op': {(op, axis):
    {'calls', 'bytes'}}}."""
    reg = reg if reg is not None else _metrics.get_registry()
    out = {'calls': 0.0, 'bytes': 0.0, 'per_op': {}}
    for metric, field in (('paddle_collective_calls_total', 'calls'),
                          ('paddle_collective_bytes_total', 'bytes')):
        fam = reg.get(metric)
        if fam is None:
            continue
        for key, child in fam.children():
            out[field] += child.value
            row = out['per_op'].setdefault(key, {'calls': 0.0, 'bytes': 0.0})
            row[field] += child.value
    return out


def device_memory_bytes() -> int:
    """Current device-memory footprint: the backend's `memory_stats()`
    when available (TPU/GPU), else the sum of live jax array bytes (the
    CPU backend reports no allocator stats)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # paddle-lint: disable=swallowed-exception -- memory_stats unsupported on this backend; live-array fallback below
        stats = None
    if stats:
        for key in ('peak_bytes_in_use', 'bytes_in_use'):
            if stats.get(key):
                return int(stats[key])
    try:
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:  # paddle-lint: disable=swallowed-exception -- live-array sum is the last-resort probe; 0 means unknown
        return 0


class StepTelemetry:
    """Per-step training telemetry into the shared registry.

    `step(loss=..., tokens=...)` once per optimizer step updates:
    paddle_steps_total, paddle_tokens_total, paddle_steps_per_sec /
    paddle_tokens_per_sec (trailing-window rates), paddle_loss_last,
    and the paddle_memory_watermark_bytes high-water gauge.
    """

    def __init__(self, registry: Optional['_metrics.MetricsRegistry'] = None,
                 window: int = 20, memory_every: int = 1):
        reg = registry if registry is not None else _metrics.get_registry()
        self._steps = reg.counter('paddle_steps_total',
                                  'optimizer steps taken')
        self._tokens = reg.counter('paddle_tokens_total',
                                   'training tokens consumed')
        self._sps = reg.gauge('paddle_steps_per_sec',
                              'trailing-window steps/sec')
        self._tps = reg.gauge('paddle_tokens_per_sec',
                              'trailing-window tokens/sec')
        self._loss = reg.gauge('paddle_loss_last', 'last observed loss')
        self._mem = reg.gauge('paddle_memory_watermark_bytes',
                              'device-memory high-water mark')
        self._times = collections.deque(maxlen=max(window, 2))
        self._tok_hist = collections.deque(maxlen=max(window, 2))
        self._memory_every = max(int(memory_every), 1)
        self._n = 0

    def step(self, loss=None, tokens: Optional[int] = None):
        if not _metrics.enabled():
            return self
        from . import flight as _flight
        from .server import note_progress
        now = time.perf_counter()
        self._times.append(now)
        self._n += 1
        self._steps.inc()
        if tokens:
            self._tokens.inc(tokens)
            self._tok_hist.append(tokens)
        if loss is not None:
            try:
                self._loss.set(float(loss))
            except (TypeError, ValueError):
                pass
        if len(self._times) >= 2:
            dt = self._times[-1] - self._times[0]
            if dt > 0:
                n = len(self._times) - 1
                self._sps.set(n / dt)
                if self._tok_hist:
                    # rate over the steps the window actually spans
                    tok = sum(list(self._tok_hist)[-n:])
                    self._tps.set(tok / dt)
        if self._n % self._memory_every == 0:
            mem = device_memory_bytes()
            self._mem.set_to_max(mem)
            _flight.get_flight_recorder().record_memory(mem)
        # liveness heartbeat (/healthz) + flight-recorder ring sample
        note_progress('step')
        _flight.get_flight_recorder().record_step(
            loss=self._loss.value if loss is not None else None,
            tokens_per_sec=self._tps.value, step=self._n)
        return self

    def phase(self, name: str, **attrs):
        """Step-phase waterfall sub-span: `with telemetry.phase(
        'data_wait'): batch = next(loader)` records a `step.{name}`
        span the goodput ledger classifies (step.data_wait ->
        host_wait, step.compute -> step_compute, ...) and the chrome
        trace renders as the per-step waterfall."""
        from . import events as _events
        return _events.span(f'step.{name}', **attrs)

    def update_memory_watermark(self):
        if _metrics.enabled():
            self._mem.set_to_max(device_memory_bytes())
        return self

    def summary(self) -> dict:
        return {'steps': self._steps.value,
                'tokens': self._tokens.value,
                'steps_per_sec': self._sps.value,
                'tokens_per_sec': self._tps.value,
                'loss_last': self._loss.value,
                'memory_watermark_bytes': self._mem.value}
