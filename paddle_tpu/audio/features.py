"""paddle.audio.features (upstream: python/paddle/audio/features/layers.py)
— Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC as nn.Layers
over signal.stft + the functional filterbanks (XLA-fused, differentiable).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer import Layer
from ..tensor import Tensor
from .. import signal
from . import functional as AF

__all__ = ['Spectrogram', 'MelSpectrogram', 'LogMelSpectrogram', 'MFCC']


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 dtype='float32'):
        super().__init__()
        self.n_fft, self.power, self.center = n_fft, power, center
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.pad_mode = pad_mode
        self.register_buffer(
            'window', AF.get_window(window, self.win_length, fftbins=True,
                                    dtype=dtype).astype(dtype))

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.window, center=self.center,
                           pad_mode=self.pad_mode)
        mag = spec.abs()
        return mag.pow(self.power) if self.power != 1.0 else mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm='slaney',
                 dtype='float32'):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.n_mels = n_mels
        self.register_buffer(
            'fbank_matrix',
            AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                    norm, dtype))

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., freq, frames]
        return self.fbank_matrix @ spec


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm='slaney',
                 ref_value=1.0, amin=1e-10, top_db=None, dtype='float32'):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return AF.power_to_db(self._melspectrogram(x), self.ref_value,
                              self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window='hann', power=2.0, center=True,
                 pad_mode='reflect', n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm='slaney', ref_value=1.0, amin=1e-10,
                 top_db=None, dtype='float32'):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer('dct_matrix',
                             AF.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        return (logmel.transpose([0, 2, 1]) @ self.dct_matrix) \
            .transpose([0, 2, 1])
