"""paddle.audio.{load,save} via the stdlib `wave` module (16-bit PCM WAV;
upstream: python/paddle/audio/backends/ delegating to soundfile — not in
this image, so WAV is the supported container).
"""
from __future__ import annotations

import wave

import numpy as np

from ..tensor import Tensor

__all__ = ['load', 'save']


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (waveform Tensor [C, T] (channels_first) float32 in [-1, 1],
    sample_rate)."""
    with wave.open(str(filepath), 'rb') as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(count)
    if width != 2:
        raise NotImplementedError('only 16-bit PCM WAV is supported')
    data = np.frombuffer(raw, dtype='<i2').reshape(-1, ch)
    out = data.astype(np.float32) / 32768.0 if normalize \
        else data.astype(np.float32)
    out = out.T if channels_first else out
    return Tensor(out), sr


def save(filepath, src, sample_rate, channels_first=True, bits_per_sample=16):
    if bits_per_sample != 16:
        raise NotImplementedError('only 16-bit PCM WAV is supported')
    data = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if data.ndim == 1:
        data = data[None, :]
    if channels_first:
        data = data.T  # -> [T, C]
    pcm = np.clip(data, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype('<i2')
    with wave.open(str(filepath), 'wb') as w:
        w.setnchannels(pcm.shape[1])
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(pcm.tobytes())
