"""paddle.audio.functional (upstream: python/paddle/audio/functional/
{window.py, functional.py}) — windows, mel filterbanks, dB conversion,
DCT — all as differentiable jnp computations.
"""
from __future__ import annotations

import math

import numpy as np

from ..tensor import Tensor

__all__ = ['get_window', 'hz_to_mel', 'mel_to_hz', 'mel_frequencies',
           'fft_frequencies', 'compute_fbank_matrix', 'power_to_db',
           'create_dct']


def _window_values(window, n, fftbins, dtype):
    if isinstance(window, (tuple, list)):
        window, *params = window
    else:
        params = []
    # periodic ("fftbins") windows are length-(n+1) symmetric windows
    # with the last sample dropped
    m = n + 1 if fftbins else n
    k = np.arange(m, dtype=np.float64)
    if window in ('hann', 'hanning'):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * k / (m - 1))
    elif window == 'hamming':
        w = 0.54 - 0.46 * np.cos(2 * math.pi * k / (m - 1))
    elif window == 'blackman':
        w = (0.42 - 0.5 * np.cos(2 * math.pi * k / (m - 1))
             + 0.08 * np.cos(4 * math.pi * k / (m - 1)))
    elif window == 'bartlett':
        w = 1.0 - np.abs(2 * k / (m - 1) - 1.0)
    elif window == 'bohman':
        x = np.abs(2 * k / (m - 1) - 1.0)
        w = (1 - x) * np.cos(math.pi * x) + np.sin(math.pi * x) / math.pi
    elif window in ('rect', 'boxcar', 'ones'):
        w = np.ones(m)
    elif window == 'gaussian':
        std = params[0] if params else 7.0
        x = k - (m - 1) / 2.0
        w = np.exp(-0.5 * (x / std) ** 2)
    elif window == 'exponential':
        tau = params[0] if params else 1.0
        x = np.abs(k - (m - 1) / 2.0)
        w = np.exp(-x / tau)
    elif window == 'triang':
        x = np.abs(2 * k - (m - 1))
        w = 1.0 - x / (m + (m % 2))
    elif window == 'cosine':
        w = np.sin(math.pi * (k + 0.5) / m)
    elif window == 'taylor':
        # 4-term Taylor window with 30 dB sidelobe level (scipy default)
        nbar, sll = 4, 30.0
        b = 10 ** (sll / 20)
        a = np.arccosh(np.asarray(b, np.float64)) / math.pi
        s2 = nbar ** 2 / (a ** 2 + (nbar - 0.5) ** 2)
        ma = np.arange(1, nbar, dtype=np.float64)
        num = np.stack([
            np.prod(1 - (mi ** 2 / s2) / (a ** 2 + (ma - 0.5) ** 2))
            for mi in ma])
        den = np.stack([
            np.prod(np.where(ma != mi, 1 - mi ** 2 / ma ** 2, 1.0))
            for mi in ma])
        fm = num / den
        x = (k - (m - 1) / 2.0) / m
        w = 1 + 2 * np.sum(
            fm[:, None] * np.cos(2 * math.pi * ma[:, None] * x[None, :]),
            axis=0)
        w = w / np.max(w)
    else:
        raise ValueError(f'unsupported window {window!r}')
    if fftbins:
        w = w[:-1]
    return w.astype(dtype)


def get_window(window, win_length, fftbins=True, dtype='float64'):
    """Window of `win_length` samples (paddle.audio.functional.get_window)."""
    return Tensor(_window_values(window, int(win_length), fftbins,
                                 np.dtype(dtype)))


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, Tensor)
    f = np.asarray(freq.numpy() if isinstance(freq, Tensor) else freq,
                    np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        # Slaney: linear below 1 kHz, log above
        mel = (f - 0.0) / (200.0 / 3)
        min_log_hz, min_log_mel = 1000.0, 1000.0 / (200.0 / 3)
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-10)
                                              / min_log_hz) / logstep, mel)
    return float(mel) if scalar else Tensor(mel)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = np.asarray(mel.numpy() if isinstance(mel, Tensor) else mel,
                    np.float64)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f = (200.0 / 3) * m
        min_log_hz, min_log_mel = 1000.0, 1000.0 / (200.0 / 3)
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar else Tensor(f)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype='float32'):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(np.asarray(mel_to_hz(Tensor(mels), htk).numpy(), dtype=np.dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype='float32'):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2,
                               dtype=np.dtype(dtype)))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm='slaney', dtype='float32'):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2] (matches
    paddle.audio.functional.compute_fbank_matrix / librosa.filters.mel)."""
    f_max = f_max or sr / 2.0
    fft_f = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk,
                            dtype='float64').numpy()
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == 'slaney':
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(np.dtype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(spect/ref) clipped to top_db below the peak. Stays a
    traced/differentiable op — it runs inside LogMelSpectrogram.forward."""
    from ..ops._helpers import defop
    import jax.numpy as jnp

    def f(x):
        db = 10.0 * jnp.log10(jnp.maximum(amin, x))
        db = db - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return defop(f, name='power_to_db')(spect)


def create_dct(n_mfcc, n_mels, norm='ortho', dtype='float32'):
    """DCT-II basis [n_mels, n_mfcc] (paddle.audio.functional.create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    basis = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == 'ortho':
        scale = np.full((n_mfcc,), math.sqrt(2.0 / n_mels))
        scale[0] = math.sqrt(1.0 / n_mels)
        basis = basis * scale[None, :]
    else:
        basis = basis * 2.0
    return Tensor(basis.astype(np.dtype(dtype)))
