"""paddle.audio.datasets (upstream: python/paddle/audio/datasets/) —
offline build: synthetic deterministic stand-ins with real shapes (see
vision.datasets for the pattern).
"""
from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ['TESS', 'ESC50']


class _SyntheticAudio(Dataset):
    """Class-dependent tones + noise so classifiers can fit."""

    def __init__(self, n, num_classes, sample_rate, duration, feat_type='raw',
                 seed=0, **feat_kwargs):
        rng = np.random.RandomState(seed)
        t = np.arange(int(sample_rate * duration)) / sample_rate
        self.labels = rng.randint(0, num_classes, n).astype(np.int64)
        freqs = 220.0 * (2.0 ** (np.arange(num_classes) / 2.0))
        sig = np.sin(2 * np.pi * freqs[self.labels][:, None] * t[None, :])
        self.waveforms = (sig + 0.05 * rng.randn(n, t.size)) \
            .astype(np.float32)
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs

    def _features(self, wav):
        if self.feat_type == 'raw':
            return wav
        from ..tensor import Tensor
        from . import features as feat_layers
        layer = {'spectrogram': feat_layers.Spectrogram,
                 'melspectrogram': feat_layers.MelSpectrogram,
                 'logmelspectrogram': feat_layers.LogMelSpectrogram,
                 'mfcc': feat_layers.MFCC}[self.feat_type](**self.feat_kwargs)
        return layer(Tensor(wav[None, :])).numpy()[0]

    def __getitem__(self, i):
        return self._features(self.waveforms[i]), self.labels[i]

    def __len__(self):
        return len(self.waveforms)


class TESS(_SyntheticAudio):
    """Toronto emotional speech set surface (7 emotion classes)."""

    def __init__(self, mode='train', n_folds=5, split=1, feat_type='raw',
                 archive=None, **kwargs):
        if archive is not None:
            raise RuntimeError('offline build: archives unavailable; '
                               'the synthetic stand-in is used instead')
        n = 200 if mode == 'train' else 50
        super().__init__(n, 7, 16000, 0.5, feat_type,
                         seed=0 if mode == 'train' else 1, **kwargs)


class ESC50(_SyntheticAudio):
    """ESC-50 environmental sounds surface (50 classes)."""

    def __init__(self, mode='train', split=1, feat_type='raw', archive=None,
                 **kwargs):
        if archive is not None:
            raise RuntimeError('offline build: archives unavailable; '
                               'the synthetic stand-in is used instead')
        n = 400 if mode == 'train' else 100
        super().__init__(n, 50, 16000, 0.5, feat_type,
                         seed=0 if mode == 'train' else 1, **kwargs)
