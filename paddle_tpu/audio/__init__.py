"""paddle.audio (upstream: python/paddle/audio/)."""
from . import backends, datasets, features, functional
from .backends import load, save

__all__ = ['backends', 'datasets', 'features', 'functional', 'load', 'save']
