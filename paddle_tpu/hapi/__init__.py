"""hapi — the high-level `paddle.Model` train/eval/predict API
(upstream: python/paddle/hapi/model.py).

TPU-native: `fit` drives the jitted donated TrainStep (one XLA program
per batch shape) rather than an eager op-by-op loop; eval/predict run a
jitted forward. The DataLoader overlaps host batch assembly with device
execution, so the step dispatch pipeline stays full.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .. import optimizer as _opt_mod
from .. import serialization
from ..io import DataLoader, Dataset
from ..jit import TrainStep, functional_call, functional_state
from ..metric import Metric
from ..nn.layer import Layer
from ..tensor import Tensor
from . import callbacks as callbacks_mod
from .callbacks import (Callback, CallbackList, EarlyStopping,
                        ReduceLROnPlateau, MetricsLoggerCallback,
                        LRSchedulerCallback, ModelCheckpoint, ProgBarLogger,
                        VisualDL)

__all__ = ['Model', 'Callback', 'EarlyStopping', 'LRSchedulerCallback',
           'ReduceLROnPlateau', 'MetricsLoggerCallback',
           'ModelCheckpoint', 'ProgBarLogger', 'VisualDL', 'callbacks_mod']


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _spanned_batches(loader):
    """Iterate `loader` with each batch fetch inside a `step.data_wait`
    span: input-pipeline stalls land in the goodput ledger's host_wait
    category (and the chrome-trace waterfall) instead of hiding in the
    unattributed residual."""
    from .. import observability as _obs
    it = iter(loader)
    while True:
        with _obs.span('step.data_wait'):
            try:
                batch = next(it)
            except StopIteration:
                return
        yield batch


def _as_loader(data, batch_size, shuffle, num_workers, drop_last):
    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)
    raise TypeError(f'expected Dataset/DataLoader, got {type(data)}')


def _feed_metric(m: Metric, out, lab):
    """compute() may return one value or a tuple destined for update()."""
    res = m.compute(out, lab)
    if isinstance(res, tuple):
        m.update(*res)
    else:
        m.update(res)


def _split_batch(batch):
    """(inputs..., label) convention: last element is the label."""
    if isinstance(batch, (list, tuple)):
        if len(batch) >= 2:
            *ins, lab = batch
            return tuple(ins), lab
        return tuple(batch), None  # 1-tuple: sole element IS the input
    return (batch,), None


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step: Optional[TrainStep] = None
        self._ft_step = None  # FaultTolerantStep wrapper, set by fit()
        self.stop_training = False

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        if loss is not None and not callable(loss):
            raise TypeError('loss must be callable (a loss Layer or fn)')
        self._amp_level = 'O0'
        self._amp_dtype = 'bfloat16'
        if amp_configs:
            from .. import amp as _amp
            cfg = ({'level': amp_configs} if isinstance(amp_configs, str)
                   else dict(amp_configs))
            level = cfg.get('level', 'O1')
            self._amp_dtype = cfg.get('dtype', 'bfloat16')
            if level == 'O2':
                out = _amp.decorate(self.network, optimizer, level='O2',
                                    dtype=self._amp_dtype)
                if optimizer is not None:
                    self.network, optimizer = out
                else:
                    self.network = out
            elif level not in ('O0', 'O1'):
                raise ValueError(f'bad amp level {level!r}')
            self._amp_level = level
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f'metric {m!r} is not a paddle.metric.Metric')
        self._train_step = None
        return self

    def _ensure_step(self):
        if self._train_step is None:
            if self._optimizer is None or self._loss is None:
                raise RuntimeError('call prepare(optimizer, loss) first')

            # close over the loss itself (not `self`) so the program
            # store's key sees WHICH loss this step bakes in
            _loss = self._loss

            def loss_fn(outputs, labels):
                out = outputs[0] if isinstance(outputs, (list, tuple)) \
                    else outputs
                return _loss(out, labels)
            self._train_step = TrainStep(self.network, loss_fn,
                                         self._optimizer)
            restored = self.__dict__.pop('_restored_opt_state', None)
            if restored is not None:
                self._train_step._opt_state = restored
        return self._train_step

    # -- batch-level API ----------------------------------------------------
    def _amp_ctx(self):
        import contextlib
        if getattr(self, '_amp_level', 'O0') == 'O1':
            from .. import amp as _amp
            return _amp.auto_cast(level='O1', dtype=self._amp_dtype)
        return contextlib.nullcontext()

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        step = self._ft_step if self._ft_step is not None \
            else self._ensure_step()
        ins = tuple(_to_list(inputs)) if isinstance(inputs, (list, tuple)) \
            else (inputs,)
        with self._amp_ctx():
            loss = step(ins if len(ins) > 1 else ins[0], labels)
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = _to_list(inputs)
        with self._amp_ctx():
            outputs = self.network(*ins)
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        res = {}
        if self._loss is not None and labels is not None:
            res['loss'] = [float(self._loss(out, labels).numpy())]
        for m in self._metrics:
            _feed_metric(m, out, labels)
        return res

    def predict_batch(self, inputs):
        self.network.eval()
        ins = _to_list(inputs)
        from .. import autograd
        with autograd.no_grad():
            out = self.network(*ins)
        return out

    # -- loops --------------------------------------------------------------
    def _save_train_ckpt(self, mgr, it, loader, force=False):
        """Step-indexed training checkpoint: model + jit opt-state + RNG
        counter + global step, with the dataloader cursor riding the
        committed sidecar so resume replays the exact remaining batches."""
        st = self._ensure_step()
        tree = {'model': dict(self.network.state_dict()),
                'opt': st._opt_state,
                'n_calls': st._n_calls,
                'step': it}
        return mgr.save(it, tree, force=force,
                        dataloader=loader
                        if hasattr(loader, 'state_dict') else None)

    def _restore_train_ckpt(self, mgr, step, loader):
        """Inverse of _save_train_ckpt; returns the restored global step."""
        from ..resilience.step import _to_device
        cursor_loader = loader if hasattr(loader, 'set_state_dict') else None
        tree = mgr.restore(step, dataloader=cursor_loader)
        self.network.set_state_dict(tree['model'])
        st = self._ensure_step()
        opt = tree.get('opt')
        st._opt_state = _to_device(opt) if opt is not None else None
        st._n_calls = int(np.asarray(tree.get('n_calls', 0)))
        return int(np.asarray(tree.get('step', 0)))

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            ckpt_dir=None, ckpt_interval=1, resume=None,
            fault_tolerance=None, step_timeout=None,
            handle_preemption=None, elastic=None):
        """Train the prepared model.

        Fault-tolerance knobs (all off by default):
          ckpt_dir: directory (or a CheckpointManager) for step-indexed
            training checkpoints every `ckpt_interval` optimizer steps,
            each committed with the dataloader cursor.
          resume: 'auto' restores the latest committed step from
            ckpt_dir (fresh run if none exist); an int restores that
            exact step. Restores params, opt-state, RNG counter, global
            step, and the mid-epoch dataloader cursor — the resumed loss
            trajectory is bit-exact vs. an uninterrupted run.
          fault_tolerance: True (defaults) or a dict of
            resilience.FaultTolerantStep kwargs — NaN/Inf and loss-spike
            steps roll back to the last snapshot and the batch is
            skipped, within a bounded skip budget.
          step_timeout: seconds before a step is declared hang-suspected
            (resilience.StepWatchdog; emits `hang_suspected`).
          handle_preemption: install SIGTERM/SIGINT handlers that force
            a synchronous checkpoint and exit the loop cleanly (defaults
            to True when ckpt_dir is set).
          elastic: True, a dict of resilience.ElasticTrainStep kwargs
            (e.g. device_source=), or a ready ElasticTrainStep —
            requires ckpt_dir. The train step becomes an elastic
            DistTrainStep over the fleet mesh; at every step boundary
            the device source is polled, and on topology change fit
            forces a sync checkpoint, rebuilds the mesh over the
            survivors (dp absorbs the change), restores the committed
            checkpoint resharded onto the new mesh, and keeps training
            (resumed trajectory bit-exact vs an uninterrupted run over
            the same topology schedule).
        """
        if accumulate_grad_batches != 1:
            raise NotImplementedError(
                'accumulate_grad_batches > 1 is not implemented yet; '
                'raise the batch size or use fleet gradient_merge')
        from .. import observability as _obs
        from .. import resilience as _res
        loader = _as_loader(train_data, batch_size, shuffle, num_workers,
                            drop_last)
        eval_loader = _as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        cbs = _to_list(callbacks)
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs = [ProgBarLogger(log_freq, verbose=verbose)] + cbs
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cblist = CallbackList(cbs)
        cblist.set_model(self)
        cblist.set_params({'epochs': epochs, 'verbose': verbose,
                           'metrics': ['loss'] + [m.name()
                                                  for m in self._metrics]})
        # ---- resilience plumbing ----------------------------------------
        mgr = None
        if ckpt_dir is not None:
            from ..utils.checkpoint import CheckpointManager
            if isinstance(ckpt_dir, CheckpointManager):
                mgr = ckpt_dir
            else:
                # npz container: structure-exact round-trips (tuples,
                # ints, None) for the jit opt-state pytree
                mgr = CheckpointManager(
                    ckpt_dir, backend='npz',
                    save_interval_steps=max(1, int(ckpt_interval)))
        if resume not in (None, False) and mgr is None:
            raise ValueError("fit(resume=...) requires ckpt_dir")
        estep = None
        if elastic:
            if mgr is None:
                raise ValueError('fit(elastic=...) requires ckpt_dir')
            from ..resilience.elastic import ElasticTrainStep
            if isinstance(elastic, ElasticTrainStep):
                estep = elastic
            else:
                if self._optimizer is None or self._loss is None:
                    raise RuntimeError('call prepare(optimizer, loss) first')
                _eloss = self._loss

                def _elastic_loss(outputs, labels):
                    out = outputs[0] \
                        if isinstance(outputs, (list, tuple)) else outputs
                    return _eloss(out, labels)
                cfg = dict(elastic) if isinstance(elastic, dict) else {}
                estep = ElasticTrainStep(self.network, _elastic_loss,
                                         self._optimizer, **cfg)
            self._train_step = estep
        # warm restart: with a persistent program store, materialize the
        # persisted train executables BEFORE the first step (a resumed
        # trainer then pays zero XLA compiles for unchanged signatures);
        # /healthz holds the ref-counted `warming` state for the
        # duration. No-op when the store has no directory.
        from .. import programs as _programs
        _pstore = _programs.get_store()
        if _pstore.persistent:
            _pstore.preload(match='train')
        it_count = 0
        start_epoch = 0
        if resume not in (None, False):
            target = mgr.latest_step() if resume == 'auto' else int(resume)
            if target is not None:   # 'auto' on an empty dir = fresh run
                it_count = self._restore_train_ckpt(mgr, target, loader)
                if hasattr(loader, 'state_dict'):
                    start_epoch = int(loader.state_dict()['epoch'])
        if fault_tolerance:
            ft_cfg = dict(fault_tolerance) \
                if isinstance(fault_tolerance, dict) else {}
            self._ft_step = _res.FaultTolerantStep(self._ensure_step(),
                                                   **ft_cfg)
        wd = _res.StepWatchdog(step_timeout) if step_timeout else None
        if handle_preemption is None:
            handle_preemption = mgr is not None
        preempt = _res.PreemptionHandler().install() \
            if handle_preemption else None

        self.stop_training = False
        cblist.on_train_begin()
        history = {'loss': []}
        epoch_logs: Dict[str, Any] = {}
        try:
            for epoch in range(start_epoch, epochs):
                if self.stop_training:
                    break
                cblist.on_epoch_begin(epoch)
                self.network.train()
                epoch_logs = {}
                for step, batch in enumerate(_spanned_batches(loader)):
                    cblist.on_train_batch_begin(step)
                    if estep is not None:
                        # elastic step boundary: re-mesh over the moved
                        # device set, round-tripping state through the
                        # committed checkpoint
                        estep.maybe_resize(
                            checkpoint_fn=lambda: self._save_train_ckpt(
                                mgr, it_count, loader, force=True),
                            restore_fn=lambda: self._restore_train_ckpt(
                                mgr, it_count, loader))
                    ins, lab = _split_batch(batch)
                    if wd is not None:
                        with wd.watch():
                            loss = self.train_batch(list(ins), lab)
                    else:
                        loss = self.train_batch(list(ins), lab)
                    skipped = self._ft_step is not None \
                        and self._ft_step.last_step_skipped
                    logs = {'loss': loss[0]}
                    cblist.on_train_batch_end(step, logs)
                    if not skipped:
                        epoch_logs.update(logs)
                        history['loss'].append(loss[0])
                        it_count += 1
                        if mgr is not None and mgr.should_save(it_count):
                            self._save_train_ckpt(mgr, it_count, loader)
                    if preempt is not None and preempt.requested:
                        # eviction grace window: one forced synchronous
                        # checkpoint (dataloader cursor included), then
                        # leave the loop cleanly
                        if mgr is not None:
                            self._save_train_ckpt(mgr, it_count, loader,
                                                  force=True)
                        if _obs.enabled():
                            _obs.get_registry().counter(
                                'paddle_resilience_preempt_saves_total',
                                'forced checkpoints on preemption '
                                'signals').inc()
                            _obs.emit('preempt_save', step=it_count,
                                      saved=mgr is not None)
                        self.stop_training = True
                    if num_iters is not None and it_count >= num_iters:
                        self.stop_training = True
                    if self.stop_training:
                        break
                if self.stop_training and preempt is not None \
                        and preempt.requested:
                    break   # skip eval: the grace window is for saving
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self._run_eval(eval_loader, cblist)
                    epoch_logs.update({f'eval_{k}': v
                                       for k, v in eval_logs.items()})
                cblist.on_epoch_end(epoch, epoch_logs)
            cblist.on_train_end(epoch_logs if epochs else {})
            if self._ft_step is not None:
                history['resilience'] = self._ft_step.stats()
        finally:
            if preempt is not None:
                preempt.uninstall()
            if wd is not None:
                wd.stop()
            self._ft_step = None
        return history

    def _run_eval(self, loader, cblist=None):
        self.network.eval()
        for m in self._metrics:
            m.reset()
        if cblist:
            cblist.on_eval_begin()
        losses = []
        from .. import autograd
        with autograd.no_grad():
            for step, batch in enumerate(loader):
                if cblist:
                    cblist.on_eval_batch_begin(step)
                ins, lab = _split_batch(batch)
                out = self.network(*ins)
                out = out[0] if isinstance(out, (list, tuple)) else out
                if self._loss is not None and lab is not None:
                    losses.append(float(self._loss(out, lab).numpy()))
                for m in self._metrics:
                    _feed_metric(m, out, lab)
                if cblist:
                    cblist.on_eval_batch_end(step)
        logs: Dict[str, Any] = {}
        if losses:
            logs['loss'] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                for n, a in zip(name, acc):
                    logs[n] = a
            else:
                logs[name] = acc
        if cblist:
            cblist.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = _as_loader(eval_data, batch_size, False, num_workers, False)
        cbs = _to_list(callbacks)
        cblist = CallbackList(cbs) if cbs else None
        if cblist:
            cblist.set_model(self)
        return self._run_eval(loader, cblist)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = _as_loader(test_data, batch_size, False, num_workers, False)
        outs = []
        for batch in loader:
            ins, _ = _split_batch(batch) if isinstance(batch, (list, tuple)) \
                else ((batch,), None)
            out = self.predict_batch(list(ins))
            out = out[0] if isinstance(out, (list, tuple)) else out
            outs.append(out.numpy())
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        serialization.save(self.network.state_dict(), path + '.pdparams')
        if training and self._optimizer is not None:
            # the live optimizer state lives inside the jitted TrainStep
            # (functional pytree), not in the eager slot dicts
            if self._train_step is not None and \
                    self._train_step._opt_state is not None:
                serialization.save(
                    {'jit_opt_state': self._train_step._opt_state},
                    path + '.pdopt')
            else:
                serialization.save(self._optimizer.state_dict(),
                                   path + '.pdopt')

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = serialization.load(path + '.pdparams')
        missing, unexpected = self.network.set_state_dict(sd)
        if not skip_mismatch and (missing or unexpected):
            raise RuntimeError(
                f'state mismatch loading model: missing={missing}, '
                f'unexpected={unexpected} (pass skip_mismatch=True to '
                f'ignore)')
        self._train_step = None
        self.__dict__.pop('_restored_opt_state', None)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + '.pdopt'):
            opt_sd = serialization.load(path + '.pdopt')
            if isinstance(opt_sd, dict) and 'jit_opt_state' in opt_sd:
                self._restored_opt_state = opt_sd['jit_opt_state']
            else:
                self._optimizer.set_state_dict(opt_sd)
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        if input_size is not None:
            from ..utils.flops import summary as _summary
            return _summary(self.network, input_size=input_size)
        total = int(sum(np.prod(p.shape) for p in self.network.parameters()))
        trainable = int(sum(np.prod(p.shape)
                            for p in self.network.parameters()
                            if not p.stop_gradient))
        lines = [repr(self.network),
                 f'Total params: {total:,}',
                 f'Trainable params: {trainable:,}']
        s = '\n'.join(lines)
        print(s)
        return {'total_params': total, 'trainable_params': trainable}
