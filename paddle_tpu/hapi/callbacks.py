"""hapi callbacks (upstream: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import math
import os
import sys
import time
import warnings
from typing import Dict, List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params: Dict = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith('on_'):
            raise AttributeError(name)

        def dispatch(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return dispatch


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            total = self.params.get('epochs')
            print(f'Epoch {epoch + 1}/{total}', file=sys.stderr)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            kv = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float)
                            else f'{k}: {v}'
                            for k, v in (logs or {}).items())
            print(f'  step {step}: {kv}', file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            kv = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float)
                            else f'{k}: {v}'
                            for k, v in (logs or {}).items())
            print(f'  epoch done in {dt:.1f}s - {kv}', file=sys.stderr)


class LRSchedulerCallback(Callback):
    """Steps an LRScheduler attached to the optimizer (upstream name:
    paddle.callbacks.LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        lr = getattr(opt, '_learning_rate', None)
        return lr if hasattr(lr, 'step') else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir='checkpoint'):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model:
            self.model.save(os.path.join(self.save_dir, 'final'))


def _resolve_mode(mode, monitor):
    """'auto' -> 'max' for accuracy-like monitors, else 'min' (shared by
    EarlyStopping and ReduceLROnPlateau)."""
    if mode == 'auto':
        return 'max' if 'acc' in monitor else 'min'
    return mode


def _extract_metric(logs, monitor):
    """Pull a scalar metric out of a hapi logs dict (metrics may arrive
    as 1-element lists); None if absent."""
    cur = (logs or {}).get(monitor)
    if cur is None:
        return None
    return float(cur[0] if isinstance(cur, (list, tuple)) else cur)


class EarlyStopping(Callback):
    def __init__(self, monitor='loss', mode='auto', patience=0,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.mode = _resolve_mode(mode, monitor)
        self.stopped = False
        self.wait = 0
        self.best = None
        self._warned_nan = False
        self._warned_missing = False

    def _better(self, cur, best):
        if best is None:
            return True
        delta = self.min_delta if self.mode == 'max' else -self.min_delta
        return cur > best + delta if self.mode == 'max' \
            else cur < best - delta

    def on_eval_end(self, logs=None):
        cur = _extract_metric(logs, self.monitor)
        if cur is None:
            if not self._warned_missing:
                self._warned_missing = True
                warnings.warn(
                    f'EarlyStopping: monitored metric {self.monitor!r} is '
                    f'missing from eval logs; callback is inactive')
            return
        # a NaN metric must never become `best` (NaN compares false
        # against everything, so every later value would look like "no
        # improvement"); treat the NaN step itself as no improvement
        if math.isnan(cur):
            if not self._warned_nan:
                self._warned_nan = True
                warnings.warn(
                    f'EarlyStopping: monitored metric {self.monitor!r} is '
                    f'NaN; treating as no improvement')
            improved = False
        else:
            improved = self._better(cur, self.best)
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                if self.model is not None:
                    self.model.stop_training = True


class VisualDL(Callback):
    """Metric logging via the JSONL summary writer
    (paddle.callbacks.VisualDL parity)."""

    def __init__(self, log_dir='vdl_log'):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _ensure_writer(self):
        if self._writer is None:  # standalone evaluate() skips train_begin
            from ..utils.logging import SummaryWriter
            self._writer = SummaryWriter(self.log_dir)
        return self._writer

    def on_train_begin(self, logs=None):
        self._ensure_writer()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        w = self._ensure_writer()
        for k, v in (logs or {}).items():
            try:
                w.add_scalar(f'train/{k}', float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        w = self._ensure_writer()
        for k, v in (logs or {}).items():
            try:
                v = v[0] if isinstance(v, (list, tuple)) else v
                w.add_scalar(f'eval/{k}', float(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._writer:
            self._writer.close()


class MetricsLoggerCallback(Callback):
    """Streams per-step train metrics into the shared observability
    registry via StepTelemetry (steps/sec, tokens/sec, last loss,
    device-memory watermark) and flags divergence with a
    debug.LossSpikeDetector whose hits land in the EventLog as
    `loss_spike` events.

    `tokens_per_batch` sets the token increment per optimizer step (e.g.
    batch_size * seq_len for an LM); when None only step rates are
    tracked. `log_dir` additionally appends registry JSONL exports every
    `export_freq` steps for plain-file tailing. `metrics_port` starts
    the HTTP observability endpoint (observability.start_server) on
    train begin, so a hapi `fit()` is scrapeable from outside the
    process (/metrics, /healthz, /summary, /events, /trace, /programs).
    """

    def __init__(self, tokens_per_batch: Optional[int] = None,
                 log_dir: Optional[str] = None, export_freq: int = 100,
                 spike_window: int = 20,
                 metrics_port: Optional[int] = None):
        super().__init__()
        self.tokens_per_batch = tokens_per_batch
        self.log_dir = log_dir
        self.export_freq = max(int(export_freq), 1)
        self._spike_window = spike_window
        self.metrics_port = metrics_port
        self.server = None
        self._telemetry = None
        self._spikes = None
        self._n = 0

    @property
    def telemetry(self):
        if self._telemetry is None:
            from .. import observability as obs
            self._telemetry = obs.StepTelemetry()
        return self._telemetry

    def on_train_begin(self, logs=None):
        from ..debug import LossSpikeDetector
        self._spikes = LossSpikeDetector(window=self._spike_window)
        self.telemetry
        if self.metrics_port is not None and self.server is None:
            from .. import observability as obs
            self.server = obs.start_server(self.metrics_port)

    def on_train_batch_end(self, step, logs=None):
        loss = (logs or {}).get('loss')
        if isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        self.telemetry.step(loss=loss, tokens=self.tokens_per_batch)
        if loss is not None and self._spikes is not None:
            self._spikes.update(loss)
        self._n += 1
        if self.log_dir and self._n % self.export_freq == 0:
            self._export()

    def on_train_end(self, logs=None):
        if self.log_dir:
            self._export()

    def _export(self):
        from .. import observability as obs
        os.makedirs(self.log_dir, exist_ok=True)
        obs.to_jsonl(path=os.path.join(self.log_dir, 'metrics.jsonl'))


# upstream name parity: paddle.callbacks.LRScheduler
# (python/paddle/hapi/callbacks.py exposes the class under this name)
LRScheduler = LRSchedulerCallback


class ReduceLROnPlateau(Callback):
    """Shrink the LR when the monitored metric plateaus (upstream
    paddle.callbacks.ReduceLROnPlateau). Works on the optimizer the
    hapi Model was prepared with."""

    def __init__(self, monitor='loss', factor=0.1, patience=10,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0.0,
                 verbose=1):
        super().__init__()
        if not 0.0 < factor < 1.0:
            raise ValueError('factor must be in (0, 1), got '
                             f'{factor!r}')
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.verbose = verbose
        self.mode = _resolve_mode(mode, monitor)
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0
        self._eval_seen_this_epoch = False
        self._warned_nan = False
        self._warned_missing = False

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == 'max':
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def _on_metric(self, logs):
        cur = _extract_metric(logs, self.monitor)
        if cur is None:
            if not self._warned_missing:
                self._warned_missing = True
                warnings.warn(
                    f'ReduceLROnPlateau: monitored metric '
                    f'{self.monitor!r} is missing from logs; callback is '
                    f'inactive')
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        # NaN must not poison `best` (see EarlyStopping): it counts as a
        # plateau step but is never stored
        if math.isnan(cur):
            if not self._warned_nan:
                self._warned_nan = True
                warnings.warn(
                    f'ReduceLROnPlateau: monitored metric '
                    f'{self.monitor!r} is NaN; treating as no improvement')
        elif self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience and self.cooldown_counter == 0:
            opt = getattr(self.model, '_optimizer', None)
            if opt is None:
                return
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f'ReduceLROnPlateau: lr {old:.2e} -> {new:.2e}')
            self.cooldown_counter = self.cooldown
            self.wait = 0

    def on_eval_end(self, logs=None):
        # eval metrics win: remember we saw them so the epoch-end train
        # metrics for the same epoch don't double-count patience
        self._eval_seen_this_epoch = True
        self._on_metric(logs)

    def on_epoch_end(self, epoch, logs=None):
        if self._eval_seen_this_epoch:
            self._eval_seen_this_epoch = False
            return
        self._on_metric(logs)
