"""paddle.profiler (upstream: python/paddle/profiler/profiler.py).

TPU-native: device-side tracing delegates to the XLA/jax profiler
(perfetto .trace.pb consumable by Perfetto UI / xprof); host-side op
timing is a lightweight in-process aggregator around `RecordEvent`
regions. `profile(dir)` is the one-liner; `Profiler` mirrors the
reference's start/stop/step object API.

Eager dispatch telemetry: every profile window also snapshots the
dispatch cache's hit/miss/retrace/fallback counters
(paddle_tpu._dispatch) so `summary()`/`export()` report how much of the
profiled region ran through cached executables vs Python re-tracing.

Observability: `RecordEvent` regions record REAL begin timestamps and
durations (per event, not a per-name running sum), feed the shared
observability EventLog/registry, and `summary()`/`export()` fold in the
registry's jit-compile, collective-bytes, and memory-watermark metrics
— the profiler and `debug.observability_summary()` read one substrate.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax

from . import _dispatch
from . import observability as _obs


_DISPATCH_KEYS = ('hits', 'misses', 'retraces', 'fallbacks', 'calls')


def _dispatch_snapshot() -> Dict[str, int]:
    s = _dispatch.stats()
    return {k: s[k] for k in _DISPATCH_KEYS}


def _dispatch_delta(since: Optional[Dict[str, int]]) -> Dict[str, int]:
    now = _dispatch_snapshot()
    if since is None:
        return now
    return {k: now[k] - since.get(k, 0) for k in _DISPATCH_KEYS}


class _HostTimer(threading.local):
    def __init__(self):
        self.stack: List = []
        self.totals: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        # per-event records with REAL begin timestamps:
        # (name, begin_perf_counter_s, duration_s)
        self.events: List[Tuple[str, float, float]] = []
        self.active = False


_host = _HostTimer()


def _host_reset():
    _host.totals.clear()
    _host.counts.clear()
    _host.events.clear()


class RecordEvent:
    """Named host region, nestable; shows up in summary() and, when a jax
    trace is active, as a TraceAnnotation on the device timeline. Each
    occurrence records its actual begin timestamp and duration (exported
    verbatim by export_chrome_tracing) and, when observability is
    enabled, lands in the shared EventLog + span histogram too."""

    def __init__(self, name: str):
        self.name = name
        self._jax_ctx = None
        self._t0 = 0.0
        self._span = None

    def begin(self):
        if _obs.enabled():
            self._span = _obs.span(self.name).begin()
        self._t0 = time.perf_counter()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:  # paddle-lint: disable=swallowed-exception -- jax profiler annotation optional; host timing still recorded
            self._jax_ctx = None
        return self

    def end(self):
        dt = time.perf_counter() - self._t0
        if _host.active:
            _host.totals[self.name] += dt
            _host.counts[self.name] += 1
            _host.events.append((self.name, self._t0, dt))
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
            self._jax_ctx = None
        if self._span is not None:
            self._span.end()
            self._span = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()


def annotate(name: str) -> RecordEvent:
    return RecordEvent(name)


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, trace_dir: Optional[str] = None):
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self._tracing = False
        self._step_count = 0
        self._step_times: List[float] = []
        self._last_step_t: Optional[float] = None
        # upstream scheduler protocol: a fn(step)->ProfilerState driving
        # windowed recording; tuple (start, end) means RECORD in [a, b)
        if isinstance(scheduler, tuple):
            a, b = scheduler
            if b <= a:
                raise ValueError(f'scheduler window ({a}, {b}) is empty')
            # upstream tuple scheduler: ONE record window [a, b)
            scheduler = make_scheduler(closed=a, ready=0, record=b - a,
                                       repeat=1)
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._window_open = False
        self._dispatch_start: Optional[Dict[str, int]] = None

    def dispatch_stats(self) -> Dict[str, int]:
        """Dispatch-cache counter deltas since start() (hits / misses /
        retraces / fallbacks / calls within the profiled region)."""
        return _dispatch_delta(self._dispatch_start)

    def start(self):
        _host.active = True
        _host_reset()
        self._dispatch_start = _dispatch_snapshot()
        if self._scheduler is not None and self._scheduler(0) in (
                ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._window_open = True
        self._last_step_t = time.perf_counter()
        if self.trace_dir and not self.timer_only:
            os.makedirs(self.trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self.trace_dir)
                self._tracing = True
            except Exception:  # paddle-lint: disable=swallowed-exception -- jax trace backend optional; _tracing=False records the posture
                self._tracing = False
        return self

    def step(self):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step_count += 1
        if self._scheduler is not None:
            # schedules are 0-based; step() is the boundary between
            # completed step (count-1) and upcoming step (count)
            rec = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
            prev = self._scheduler(self._step_count - 1)
            if self._window_open and (
                    prev == ProfilerState.RECORD_AND_RETURN
                    or prev not in rec):
                self._window_open = False
                if self._on_trace_ready is not None:
                    self._on_trace_ready(self)
            if not self._window_open \
                    and self._scheduler(self._step_count) in rec:
                self._window_open = True
                # a window exports ITS steps only: reset the host
                # aggregates when it opens
                _host_reset()

    def stop(self):
        # a scheduler window still open at stop() owns real data (e.g. a
        # RECORD phase the loop exited mid-cycle): flush it to
        # on_trace_ready before deactivating, instead of dropping it
        if self._window_open:
            self._window_open = False
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)
        _host.active = False
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            finally:
                self._tracing = False
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by='total', max_rows=30) -> str:
        rows = sorted(_host.totals.items(), key=lambda kv: -kv[1])
        lines = [f'{"region":<40}{"calls":>8}{"total_s":>12}{"avg_ms":>10}']
        for name, total in rows[:max_rows]:
            n = _host.counts[name]
            lines.append(
                f'{name:<40}{n:>8}{total:>12.4f}{total / n * 1e3:>10.2f}')
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            lines.append(f'steps: {self._step_count}, avg step '
                         f'{avg * 1e3:.2f} ms')
        d = self.dispatch_stats()
        if d['calls']:
            rate = d['hits'] / d['calls']
            lines.append(
                f'eager dispatch: {d["calls"]} ops, {rate:.1%} cache hits'
                f' ({d["misses"]} misses, {d["retraces"]} retraces, '
                f'{d["fallbacks"]} fallbacks)')
        # shared observability registry: compile time / comm bytes /
        # memory watermark recorded by the instrumented runtime
        reg = _obs.get_registry()
        compiles = reg.value('paddle_jit_compiles_total')
        if compiles:
            lines.append(
                f'jit: {int(compiles)} XLA compiles, '
                f'{reg.value("paddle_jit_compile_seconds_total"):.3f} s')
        comm = _obs.collective_totals(reg)
        if comm['calls']:
            lines.append(f'collectives: {int(comm["calls"])} calls, '
                         f'{int(comm["bytes"])} bytes')
        mem = reg.value('paddle_memory_watermark_bytes')
        if mem:
            lines.append(f'memory watermark: {mem / 2**20:.1f} MiB')
        s = '\n'.join(lines)
        return s

    def export(self, path: str):
        with open(path, 'w') as f:
            json.dump({'regions': {k: {'total_s': v,
                                       'calls': _host.counts[k]}
                                   for k, v in _host.totals.items()},
                       'step_times': self._step_times,
                       'dispatch': self.dispatch_stats(),
                       'observability': _obs.get_registry().snapshot()}, f)


@contextlib.contextmanager
def profile(trace_dir: Optional[str] = None, timer_only=False):
    """`with paddle_tpu.profiler.profile('/tmp/trace'):` — wraps
    jax.profiler.trace + host region timing."""
    p = Profiler(trace_dir=trace_dir, timer_only=timer_only)
    p.start()
    try:
        yield p
    finally:
        p.stop()


class ProfilerState:
    """Scheduler states (upstream paddle.profiler.ProfilerState)."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget:
    """Hardware targets (upstream paddle.profiler.ProfilerTarget);
    CUSTOM_DEVICE covers the TPU backend here."""
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 3  # alias: the custom device of this build


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Windowed profiling schedule (upstream
    paddle.profiler.make_scheduler): skip_first steps, then cycles of
    closed -> ready -> record; repeat=0 cycles forever."""
    cycle = closed + ready + record
    if cycle <= 0:
        raise ValueError('closed + ready + record must be positive')

    def schedule(step: int) -> int:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return schedule


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """on_trace_ready factory writing chrome://tracing JSON of the host
    regions (upstream paddle.profiler.export_chrome_tracing). Each
    RecordEvent occurrence is emitted at its REAL begin timestamp with
    its real duration — a true timeline, not name-aggregated events at
    fabricated back-to-back offsets. Device timelines ride the jax
    perfetto trace in `trace_dir`."""
    def handler(prof: 'Profiler'):
        os.makedirs(dir_name, exist_ok=True)
        events = []
        counts: Dict[str, int] = collections.defaultdict(int)
        window = sorted(_host.events, key=lambda e: e[1])
        origin = window[0][1] if window else 0.0
        for name, t0, dur in window:
            counts[name] += 1
            events.append({
                'name': name, 'ph': 'X', 'pid': 0,
                'tid': worker_name or 'host',
                'ts': int((t0 - origin) * 1e6), 'dur': int(dur * 1e6),
                'args': {'calls': counts[name]},
            })
        path = os.path.join(
            dir_name, f'paddle_tpu_trace_{prof._step_count}.json')
        with open(path, 'w') as f:
            json.dump({'traceEvents': events}, f)
        return path
    return handler


def load_profiler_result(path: str):
    """Read back a chrome-tracing JSON written by
    export_chrome_tracing (upstream load_profiler_result)."""
    with open(path) as f:
        return json.load(f)
