"""paddle.profiler (upstream: python/paddle/profiler/profiler.py).

TPU-native: device-side tracing delegates to the XLA/jax profiler
(perfetto .trace.pb consumable by Perfetto UI / xprof); host-side op
timing is a lightweight in-process aggregator around `RecordEvent`
regions. `profile(dir)` is the one-liner; `Profiler` mirrors the
reference's start/stop/step object API.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

import jax


class _HostTimer(threading.local):
    def __init__(self):
        self.stack: List = []
        self.totals: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        self.active = False


_host = _HostTimer()


class RecordEvent:
    """Named host region, nestable; shows up in summary() and, when a jax
    trace is active, as a TraceAnnotation on the device timeline."""

    def __init__(self, name: str):
        self.name = name
        self._jax_ctx = None
        self._t0 = 0.0

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None
        return self

    def end(self):
        dt = time.perf_counter() - self._t0
        if _host.active:
            _host.totals[self.name] += dt
            _host.counts[self.name] += 1
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
            self._jax_ctx = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()


def annotate(name: str) -> RecordEvent:
    return RecordEvent(name)


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, trace_dir: Optional[str] = None):
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self._tracing = False
        self._step_count = 0
        self._step_times: List[float] = []
        self._last_step_t: Optional[float] = None

    def start(self):
        _host.active = True
        _host.totals.clear()
        _host.counts.clear()
        self._last_step_t = time.perf_counter()
        if self.trace_dir and not self.timer_only:
            os.makedirs(self.trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self.trace_dir)
                self._tracing = True
            except Exception:
                self._tracing = False
        return self

    def step(self):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step_count += 1

    def stop(self):
        _host.active = False
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            finally:
                self._tracing = False
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by='total', max_rows=30) -> str:
        rows = sorted(_host.totals.items(), key=lambda kv: -kv[1])
        lines = [f'{"region":<40}{"calls":>8}{"total_s":>12}{"avg_ms":>10}']
        for name, total in rows[:max_rows]:
            n = _host.counts[name]
            lines.append(
                f'{name:<40}{n:>8}{total:>12.4f}{total / n * 1e3:>10.2f}')
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            lines.append(f'steps: {self._step_count}, avg step '
                         f'{avg * 1e3:.2f} ms')
        s = '\n'.join(lines)
        return s

    def export(self, path: str):
        with open(path, 'w') as f:
            json.dump({'regions': {k: {'total_s': v,
                                       'calls': _host.counts[k]}
                                   for k, v in _host.totals.items()},
                       'step_times': self._step_times}, f)


@contextlib.contextmanager
def profile(trace_dir: Optional[str] = None, timer_only=False):
    """`with paddle_tpu.profiler.profile('/tmp/trace'):` — wraps
    jax.profiler.trace + host region timing."""
    p = Profiler(trace_dir=trace_dir, timer_only=timer_only)
    p.start()
    try:
        yield p
    finally:
        p.stop()
