"""paddle.geometric — graph message-passing ops (upstream:
python/paddle/geometric/: math.py segment ops, message_passing/send_recv.py).

TPU-native design: every op lowers to `jax.ops.segment_*` — XLA turns
these into sorted-scatter reductions, which is exactly how the
reference's CUDA segment kernels behave, minus the hand-written atomics.
`out_size`/eager-max give the static segment count jit needs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops._helpers import defop
from .tensor import to_jax

__all__ = ['segment_sum', 'segment_mean', 'segment_min', 'segment_max',
           'send_u_recv', 'send_ue_recv']


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids_val = to_jax(ids)
    if isinstance(ids_val, jax.core.Tracer):
        raise ValueError(
            'segment ops need a static segment count under jit: pass '
            'out_size=<num_segments> when calling from traced code')
    return int(jax.device_get(jnp.max(ids_val))) + 1


def _segment(op_name):
    jfn = getattr(jax.ops, f'segment_{op_name}')

    def f(data, segment_ids, out_size=None, name=None):
        # out_size is a jit escape hatch (an extension over upstream's
        # signature): segment_ids is a tracer under jit, so the eager
        # max cannot run — pass the static segment count instead
        n = _num_segments(segment_ids, out_size)

        def g(d, ids):
            return jfn(d, ids, num_segments=n)
        return defop(g, name=f'segment_{op_name}')(data, segment_ids)
    f.__name__ = f'segment_{op_name}'
    return f


segment_sum = _segment('sum')
segment_min = _segment('min')
segment_max = _segment('max')


def segment_mean(data, segment_ids, out_size=None, name=None):
    n = _num_segments(segment_ids, out_size)

    def g(d, ids):
        tot = jax.ops.segment_sum(d, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(ids.shape[0], d.dtype), ids,
                                  num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return tot / jnp.maximum(cnt.reshape(shape), 1)
    return defop(g, name='segment_mean')(data, segment_ids)


_REDUCERS = {'sum': 'sum', 'mean': 'mean', 'min': 'min', 'max': 'max'}


def send_u_recv(x, src_index, dst_index, reduce_op='sum', out_size=None,
                name=None):
    """Gather `x` rows at src_index, reduce them into dst_index buckets
    (upstream: paddle.geometric.send_u_recv)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f'unsupported reduce_op {reduce_op!r}')
    n = out_size if out_size is not None \
        else _num_segments(dst_index, None)
    n = max(int(n), int(to_jax(x).shape[0]) if out_size is None else int(n))

    def g(xv, src, dst):
        msgs = xv[src]
        if reduce_op == 'mean':
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape[0], xv.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(
                cnt.reshape((n,) + (1,) * (msgs.ndim - 1)), 1)
        out = getattr(jax.ops, f'segment_{reduce_op}')(
            msgs, dst, num_segments=n)
        if reduce_op in ('min', 'max'):
            # empty buckets come back +/-inf; upstream zeroes them
            out = jnp.where(jnp.isinf(out), jnp.zeros_like(out), out)
        return out
    return defop(g, name='send_u_recv')(x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op='add',
                 reduce_op='sum', out_size=None, name=None):
    """Message = x[src] (op) y[edge]; then reduce into dst buckets
    (upstream: paddle.geometric.send_ue_recv)."""
    ops_ = {'add': jnp.add, 'sub': jnp.subtract, 'mul': jnp.multiply,
            'div': jnp.divide}
    if message_op not in ops_:
        raise ValueError(f'unsupported message_op {message_op!r}')
    if reduce_op not in _REDUCERS:
        raise ValueError(f'unsupported reduce_op {reduce_op!r}')
    n = out_size if out_size is not None \
        else _num_segments(dst_index, None)
    n = max(int(n), int(to_jax(x).shape[0]) if out_size is None else int(n))

    def g(xv, yv, src, dst):
        msgs = ops_[message_op](xv[src], yv)
        if reduce_op == 'mean':
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape[0], tot.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(
                cnt.reshape((n,) + (1,) * (msgs.ndim - 1)), 1)
        out = getattr(jax.ops, f'segment_{reduce_op}')(
            msgs, dst, num_segments=n)
        if reduce_op in ('min', 'max'):
            out = jnp.where(jnp.isinf(out), jnp.zeros_like(out), out)
        return out
    return defop(g, name='send_ue_recv')(x, y, src_index, dst_index)
