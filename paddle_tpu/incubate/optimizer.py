"""paddle.incubate.optimizer (upstream:
python/paddle/incubate/optimizer/): LookAhead and ModelAverage wrappers.

Both keep their auxiliary state as jax arrays updated functionally —
no in-place device mutation, so they compose with jit exactly like the
core optimizers."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor


class LookAhead:
    """Wraps an inner optimizer: every k steps the slow weights move
    alpha of the way toward the fast weights, and the fast weights are
    reset onto them (upstream incubate.optimizer.LookAhead; Zhang et
    al. 2019)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError('alpha must be in [0, 1]')
        if k < 1:
            raise ValueError('k must be >= 1')
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step_count = 0
        self._slow = None

    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        params = self._params()
        if self._slow is None:
            self._slow = [p.value for p in params]
        if self._step_count % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p.value - self._slow[i])
                self._slow[i] = slow
                p._data = slow.astype(p.value.dtype)
                p._node = None

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, v):
        return self.inner_optimizer.set_lr(v)

    def state_dict(self):
        return {'inner': self.inner_optimizer.state_dict(),
                'step_count': self._step_count,
                'slow': self._slow}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd['inner'])
        self._step_count = sd['step_count']
        self._slow = sd['slow']


class ModelAverage:
    """Running average of parameters for evaluation (upstream
    incubate.optimizer.ModelAverage): accumulate each step; apply()
    swaps averaged weights in (restore() swaps back)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError('ModelAverage needs the parameter list')
        self._parameters = list(parameters)
        self.max_average_window = int(max_average_window)
        self._sums = [jnp.zeros_like(p.value) for p in self._parameters]
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current weights into the window."""
        if self._count >= self.max_average_window:
            # restart the window like upstream when it saturates
            self._sums = [jnp.zeros_like(p.value)
                          for p in self._parameters]
            self._count = 0
        self._sums = [s + p.value
                      for s, p in zip(self._sums, self._parameters)]
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap the averaged weights into the live parameters. A second
        apply() without restore() keeps the ORIGINAL training weights as
        the restore point; need_restore=False discards it (final swap,
        upstream semantics)."""
        if self._count == 0:
            return
        if self._backup is None:
            self._backup = [p.value for p in self._parameters]
        for p, s in zip(self._parameters, self._sums):
            p._data = (s / self._count).astype(p.value.dtype)
            p._node = None
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        """Undo apply(): put the training weights back."""
        if self._backup is None:
            return
        for p, b in zip(self._parameters, self._backup):
            p._data = b
            p._node = None
        self._backup = None
