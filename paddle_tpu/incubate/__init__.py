"""paddle.incubate — experimental-API surface (upstream: python/paddle/incubate/).

On TPU the "fused" incubate ops are the natural form: XLA fuses the
norm/matmul/activation chains these APIs name, and the attention core
rides the same flash path as F.scaled_dot_product_attention. The module
exists for import-path parity; the implementations delegate to the
already-fused compute paths.
"""
from . import autograd
from . import nn
from . import optimizer
from ..geometric import segment_sum, segment_mean, segment_min, segment_max

__all__ = ['autograd', 'nn', 'optimizer', 'segment_sum', 'segment_mean',
           'segment_min', 'segment_max', 'graph_send_recv']


def graph_send_recv(x, src_index, dst_index, pool_type='sum', out_size=None,
                    name=None):
    """Pre-2.4 name of geometric.send_u_recv (upstream:
    python/paddle/incubate/operators/graph_send_recv.py)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)
