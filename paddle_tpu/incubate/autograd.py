"""paddle.incubate.autograd (upstream:
python/paddle/incubate/autograd/): functional forward/reverse
differentiation — jvp, vjp, Jacobian, Hessian.

TPU-native: these are direct jax transforms over a functionalized call;
forward-mode (jvp) is native here where the reference emulates it with
double-vjp."""
from __future__ import annotations

import jax

from ..tensor import Tensor, apply_op, to_jax


def _functionalize(func):
    """Wrap a Tensor-level callable into a raw-array callable."""
    def raw(*vals):
        from ..autograd import functional_scope
        wrapped = [Tensor(v) for v in vals]
        with functional_scope():
            out = func(*wrapped)
        if isinstance(out, (tuple, list)):
            return tuple(o.value if isinstance(o, Tensor) else o
                         for o in out)
        return out.value if isinstance(out, Tensor) else out
    return raw


def _as_vals(xs):
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    return tuple(to_jax(x) for x in xs)


def jvp(func, xs, v=None):
    """Forward-mode: (func(xs), J·v). v defaults to ones (upstream
    incubate.autograd.jvp)."""
    vals = _as_vals(xs)
    tangents = _as_vals(v) if v is not None else tuple(
        jax.numpy.ones_like(x) for x in vals)
    out, tang = jax.jvp(_functionalize(func), vals, tangents)
    wrap = lambda o: tuple(Tensor(t) for t in o) \
        if isinstance(o, tuple) else Tensor(o)
    return wrap(out), wrap(tang)


def vjp(func, xs, v=None):
    """Reverse-mode: (func(xs), vᵀ·J). v defaults to ones and must
    mirror the output structure for multi-output funcs (upstream
    incubate.autograd.vjp)."""
    vals = _as_vals(xs)
    out, pull = jax.vjp(_functionalize(func), *vals)
    if v is not None:
        cvals = _as_vals(v)
        cot = tuple(cvals) if isinstance(out, tuple) else cvals[0]
        if isinstance(out, tuple) and len(cvals) != len(out):
            raise ValueError(f'v has {len(cvals)} cotangents for '
                             f'{len(out)} outputs')
    else:
        cot = jax.tree_util.tree_map(jax.numpy.ones_like, out)
    grads = pull(cot)
    gw = tuple(Tensor(g) for g in grads)
    return Tensor(out) if not isinstance(out, tuple) \
        else tuple(Tensor(o) for o in out), \
        gw if len(gw) > 1 else gw[0]


class Jacobian:
    """Lazy full Jacobian of func at xs (upstream
    incubate.autograd.Jacobian): index/slice like an array; [:] gives
    the whole matrix."""

    def __init__(self, func, xs, is_batched=False):
        import jax.numpy as jnp
        vals = _as_vals(xs)
        f = _functionalize(func)
        argnums = tuple(range(len(vals)))
        if is_batched:
            jac = jax.vmap(jax.jacrev(f, argnums=argnums))(*vals)
        else:
            jac = jax.jacrev(f, argnums=argnums)(*vals)
        if len(vals) == 1:
            self._jac = jac[0]
        else:
            # multiple inputs: flatten each input's dims and concat the
            # blocks along the last axis (out_dims..., sum n_i)
            out_ndim = jac[0].ndim - vals[0].ndim
            blocks = [j.reshape(j.shape[:out_ndim] + (-1,)) for j in jac]
            self._jac = jnp.concatenate(blocks, axis=-1)

    def __getitem__(self, idx):
        return Tensor(self._jac[idx])

    @property
    def shape(self):
        return list(self._jac.shape)


class Hessian:
    """Lazy Hessian of a scalar func at xs (upstream
    incubate.autograd.Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        vals = _as_vals(xs)
        f = _functionalize(func)

        def scalar(*a):
            out = f(*a)
            return out.reshape(())
        import jax.numpy as jnp
        argnums = tuple(range(len(vals)))
        if is_batched:
            hes = jax.vmap(jax.hessian(scalar, argnums=argnums))(*vals)
        else:
            hes = jax.hessian(scalar, argnums=argnums)(*vals)
        if len(vals) == 1:
            self._hes = hes[0][0]
        else:
            # assemble the full block matrix over flattened inputs
            sizes = [int(jnp.size(v)) for v in vals]
            rows = []
            for i, hrow in enumerate(hes):
                row = [h.reshape(sizes[i], sizes[j])
                       for j, h in enumerate(hrow)]
                rows.append(jnp.concatenate(row, axis=1))
            self._hes = jnp.concatenate(rows, axis=0)

    def __getitem__(self, idx):
        return Tensor(self._hes[idx])

    @property
    def shape(self):
        return list(self._hes.shape)
