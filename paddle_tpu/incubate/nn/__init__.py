from . import functional
from .functional import memory_efficient_attention

__all__ = ['functional', 'memory_efficient_attention']
