"""paddle.incubate.nn.functional — fused-op API surface (upstream:
python/paddle/incubate/nn/functional/: fused_transformer.py,
fused_matmul_bias.py, fused_dropout_add.py, fused_rms_norm.py, swiglu.py).

TPU-native note: upstream backs each of these with a monolithic CUDA
kernel; here each is an ordinary jnp chain around the framework's
already-fused cores (pallas flash attention via
F.scaled_dot_product_attention, pallas RMSNorm) — XLA fuses the
norm/bias/residual epilogues into the surrounding matmuls, which is the
whole point of these APIs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...ops._helpers import defop

__all__ = ['fused_linear', 'fused_matmul_bias', 'fused_dropout_add',
           'fused_rms_norm', 'fused_layer_norm', 'swiglu',
           'fused_multi_head_attention', 'fused_feedforward',
           'memory_efficient_attention',
           'fused_rotary_position_embedding']


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(xv, wv, *b):
        wv = wv.T if transpose_weight else wv
        out = xv @ wv
        return out + b[0] if b else out
    args = (x, weight) if bias is None else (x, weight, bias)
    return defop(f, name='fused_linear')(*args)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    def f(xv, yv, *b):
        xv = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        yv = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = xv @ yv
        return out + b[0] if b else out
    args = (x, y) if bias is None else (x, y, bias)
    return defop(f, name='fused_matmul_bias')(*args)


def fused_dropout_add(x, y, p=0.5, training=True, mode='upscale_in_train',
                      name=None):
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    if begin_norm_axis not in (-1, None) and \
            begin_norm_axis != len(x.shape) - 1:
        raise NotImplementedError('fused_rms_norm normalizes the last axis')
    return F.rms_norm(x, weight=norm_weight, bias=norm_bias, epsilon=epsilon)


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, name=None):
    if begin_norm_axis not in (-1, None) and \
            begin_norm_axis != len(x.shape) - 1:
        raise NotImplementedError('fused_layer_norm normalizes the last axis')
    return F.layer_norm(x, x.shape[-1], weight=norm_weight, bias=norm_bias,
                        epsilon=epsilon)


def swiglu(x, y=None, name=None):
    """silu(x) * y; with y=None, x is split in half on the last axis
    (upstream: python/paddle/incubate/nn/functional/swiglu.py)."""
    if y is None:
        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b
        return defop(f, name='swiglu')(x)
    return defop(lambda a, b: jax.nn.silu(a) * b, name='swiglu')(x, y)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.0, attn_dropout_rate=0.0,
        ln_epsilon=1e-5, training=True, mode='upscale_in_train', ring_id=-1,
        add_residual=True, name=None):
    """Pre/post-LN multi-head self-attention block (upstream:
    paddle.incubate.nn.functional.fused_multi_head_attention).

    x: [B, S, E]; qkv_weight: [3, num_heads, head_dim, E];
    qkv_bias: [3, num_heads, head_dim]; linear_weight: [E, E].
    The attention core is F.scaled_dot_product_attention (pallas flash
    path); everything around it is XLA-fused epilogue.
    """
    if cache_kv is not None or ring_id != -1:
        raise NotImplementedError('cache_kv/ring_id are not supported')
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)

    def qkv_f(hv, wv, *b):
        # [B,S,E] x [3,N,H,E] -> [3,B,S,N,H]
        out = jnp.einsum('bse,tnhe->tbsnh', hv, wv)
        return out + b[0][:, None, None] if b else out
    qkv_args = (h, qkv_weight) if qkv_bias is None else (h, qkv_weight,
                                                         qkv_bias)
    qkv = defop(qkv_f, name='fused_qkv')(*qkv_args)
    q, k, v = qkv[0], qkv[1], qkv[2]
    attn = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)

    def proj_f(av, wv, *b):
        bsz, s = av.shape[0], av.shape[1]
        out = av.reshape(bsz, s, -1) @ wv
        return out + b[0] if b else out
    proj_args = (attn, linear_weight) if linear_bias is None else (
        attn, linear_weight, linear_bias)
    out = defop(proj_f, name='fused_out_proj')(*proj_args)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation='relu', ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode='upscale_in_train', ring_id=-1, add_residual=True,
                      name=None):
    """LN -> linear1 -> act -> dropout -> linear2 -> dropout -> +residual
    (upstream: paddle.incubate.nn.functional.fused_feedforward)."""
    if ring_id != -1:
        raise NotImplementedError('ring_id is not supported')
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = fused_linear(h, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = residual + h
    if not pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1], weight=ln2_scale, bias=ln2_bias,
                         epsilon=ln2_epsilon)
    return h


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True, name=None):
    """Apply RoPE to q/k/v ([B, S, N, H] layout; upstream:
    paddle.incubate.nn.functional.fused_rotary_position_embedding).
    sin/cos: [1, S, 1, H] (or broadcastable); default angles are computed
    with the standard 10000^(-2i/H) frequencies when not given."""

    def make_sin_cos(s, hdim, dtype):
        inv = 1.0 / (10000.0 ** (jnp.arange(0, hdim, 2,
                                            dtype=jnp.float32) / hdim))
        pos = jnp.arange(s, dtype=jnp.float32)
        ang = jnp.outer(pos, inv)  # [S, H/2]
        if use_neox_rotary_style:
            ang = jnp.concatenate([ang, ang], axis=-1)
        else:
            ang = jnp.repeat(ang, 2, axis=-1)
        return (jnp.sin(ang)[None, :, None, :].astype(dtype),
                jnp.cos(ang)[None, :, None, :].astype(dtype))

    def rot_half(t):
        if use_neox_rotary_style:
            h1, h2 = jnp.split(t, 2, axis=-1)
            return jnp.concatenate([-h2, h1], axis=-1)
        t2 = t.reshape(t.shape[:-1] + (-1, 2))
        rot = jnp.stack([-t2[..., 1], t2[..., 0]], axis=-1)
        return rot.reshape(t.shape)

    def apply_one(t, sv, cv, pos):
        if pos is not None:
            sv = jnp.squeeze(sv, (0, 2))[pos][:, :, None, :]
            cv = jnp.squeeze(cv, (0, 2))[pos][:, :, None, :]
        return t * cv + rot_half(t) * sv

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue

        def f(tv, *rest):
            i = 0
            sv = cv = pv = None
            if sin is not None:
                sv, cv = rest[0], rest[1]
                i = 2
            if position_ids is not None:
                pv = rest[i]
            if sv is None:
                sv, cv = make_sin_cos(tv.shape[1] if pv is None
                                      else int(jnp.max(pv)) + 1,
                                      tv.shape[-1], tv.dtype)
            return apply_one(tv, sv, cv, pv)
        args = [t]
        if sin is not None:
            args += [sin, cos]
        if position_ids is not None:
            args.append(position_ids)
        outs.append(defop(f, name='fused_rope')(*args))
    return tuple(outs)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, name=None):
    """upstream paddle.incubate.nn.memory_efficient_attention (the
    xformers-style API): on TPU this IS the flash path —
    F.scaled_dot_product_attention lowers to the pallas kernel, which
    never materializes the [B, H, Sq, Sk] logits."""
    if scale is not None:
        query = query * scale * (query.shape[-1] ** 0.5)
    return F.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias, dropout_p=p,
        is_causal=False, training=training)
