"""Failure detection (upstream: paddle.amp.debugging / check_nan_inf,
python/paddle/amp/debugging.py + the fleet loss-spike monitor).

- `check_numerics(x, name)` — raises on NaN/Inf in eager mode; under
  jit it routes through `jax.debug` safe-guarding via checkify-style
  host callback only when enabled (zero overhead when off).
- `enable_check_numerics()` — installs a tape-level hook: every op
  recorded by apply_op is scanned for non-finite outputs (eager only,
  the DyGraph debugging workflow).
- `LossSpikeDetector` — windowed z-score monitor used by hapi/fleet to
  flag divergence (upstream: loss scaling skip-counters + spike logs).
- dispatch telemetry — `dispatch_stats()` / `dispatch_summary()` read the
  eager dispatch cache's hit/miss/retrace/fallback counters
  (paddle_tpu._dispatch); `enable_dispatch_cache(False)` forces every op
  back onto the uncached slow path (A/B debugging, parity checks).
- `observability_summary()` — the one-call report over the shared
  observability registry: dispatch hit-rate, jit compile count/seconds,
  per-axis collective calls + bytes, offload transfer bytes, step/token
  throughput, memory watermark, and host-span timings.
"""
from __future__ import annotations

import collections
import math
from typing import Deque, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import _dispatch
from . import flags as _flags
from . import observability as _obs
from .tensor import Tensor


class NumericsError(RuntimeError):
    pass


def check_numerics(x, name: str = 'tensor', raise_on_error: bool = True):
    """Assert a tensor is finite. Eager: host check with a precise count.
    Traced: uses jax.debug.callback so the check travels into the XLA
    program (no effect on the computed value)."""
    val = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if not jnp.issubdtype(val.dtype, jnp.floating):
        return x

    if isinstance(val, jax.core.Tracer):
        def cb(n_nan, n_inf):
            if int(n_nan) or int(n_inf):
                msg = (f'check_numerics({name}): {int(n_nan)} NaN, '
                       f'{int(n_inf)} Inf values')
                if raise_on_error:
                    raise NumericsError(msg)
                print(msg)
        f32 = val.astype(jnp.float32)
        jax.debug.callback(cb, jnp.isnan(f32).sum(),
                           jnp.isinf(f32).sum())
        return x

    f32 = np.asarray(val, np.float32)
    n_nan = int(np.isnan(f32).sum())
    n_inf = int(np.isinf(f32).sum())
    if n_nan or n_inf:
        msg = (f'check_numerics({name}): {n_nan} NaN, {n_inf} Inf of '
               f'{f32.size} values, shape {tuple(f32.shape)}')
        if raise_on_error:
            raise NumericsError(msg)
        print(msg)
    return x


# ---------------------------------------------------------------------------
# tape-level monitor (FLAGS_check_nan_inf)
# ---------------------------------------------------------------------------

def _scan_outputs(out, op_name):
    def scan(t):
        if isinstance(t, Tensor) and not isinstance(
                t.value, jax.core.Tracer):
            check_numerics(t, name=op_name or 'op')
        return t
    jax.tree_util.tree_map(scan, out,
                           is_leaf=lambda v: isinstance(v, Tensor))


def enable_check_numerics(level: int = 0):
    """Scan every eager op output for NaN/Inf via the apply_op hook
    (upstream: FLAGS_check_nan_inf=1). Heavy — debugging only."""
    from . import tensor as tmod
    _flags.set_flags({'FLAGS_check_nan_inf': True,
                      'FLAGS_check_nan_inf_level': level})
    tmod._numerics_hook = _scan_outputs


def disable_check_numerics():
    from . import tensor as tmod
    _flags.set_flags({'FLAGS_check_nan_inf': False})
    tmod._numerics_hook = None


# ---------------------------------------------------------------------------
# eager dispatch cache telemetry (paddle_tpu._dispatch)
# ---------------------------------------------------------------------------

def dispatch_stats() -> dict:
    """Counters for the eager dispatch fast path: hits (op served from a
    cached executable), misses (a trace/compile happened), retraces
    (misses whose op signature had already been compiled — shape/static
    churn), fallbacks (unkeyable calls on the slow path), plus
    hit_rate/cache_size and a per-op breakdown. Steady-state eager
    training should show zero retraces after warmup."""
    return _dispatch.stats()


def reset_dispatch_stats():
    _dispatch.reset_stats()


def clear_dispatch_cache():
    """Drop every cached executable (counters survive; pair with
    reset_dispatch_stats() for a clean measurement window)."""
    _dispatch.clear()


def enable_dispatch_cache(enable: bool = True):
    """Toggle the eager dispatch cache (FLAGS_eager_dispatch_cache).
    Disabling routes every apply_op through the per-call jax.vjp slow
    path — the pre-cache behavior — for A/B parity or debugging."""
    _dispatch.enable(enable)


def disable_dispatch_cache():
    _dispatch.enable(False)


def dispatch_summary(max_rows: int = 15) -> str:
    """Human-readable dispatch-cache report (global counters + the
    hottest ops by call count)."""
    s = _dispatch.stats()
    lines = [
        'eager dispatch cache: '
        f'{"enabled" if s["enabled"] else "DISABLED"}',
        f'  calls {s["calls"]}  hits {s["hits"]}  misses {s["misses"]}'
        f'  retraces {s["retraces"]}  fallbacks {s["fallbacks"]}'
        f'  hit_rate {s["hit_rate"]:.1%}',
        f'  cache_size {s["cache_size"]}  evictions {s["evictions"]}'
        f'  errors {s["errors"]}',
    ]
    per = sorted(s['per_op'].items(),
                 key=lambda kv: -(kv[1]['hits'] + kv[1]['misses']
                                  + kv[1]['fallbacks']))
    if per:
        lines.append(f'  {"op":<28}{"hits":>8}{"misses":>8}{"fallbacks":>10}')
        for name, row in per[:max_rows]:
            lines.append(f'  {name or "<unnamed>":<28}{row["hits"]:>8}'
                         f'{row["misses"]:>8}{row["fallbacks"]:>10}')
    return '\n'.join(lines)


def _observability_data(max_rows: int = 10) -> dict:
    """The machine-readable structure behind observability_summary():
    one JSON-able dict per section, read off the same registry snapshot
    the text report formats."""
    reg = _obs.get_registry()
    snap = reg.snapshot()   # runs collectors (dispatch mirror) first
    ds = _dispatch.stats()
    comm = _obs.collective_totals(reg)
    spans = reg.get('paddle_span_seconds')
    span_rows = []
    if spans is not None:
        for key, child in sorted(spans.children(),
                                 key=lambda kv: -kv[1].sum)[:max_rows]:
            span_rows.append({
                'name': key[0], 'calls': child.count,
                'total_s': child.sum,
                'avg_ms': (child.sum / child.count * 1e3
                           if child.count else 0.0)})
    log = _obs.get_event_log()
    return {
        'process_index': snap['process_index'],
        'dispatch': {
            'calls': ds['calls'], 'hit_rate': ds['hit_rate'],
            'misses': ds['misses'], 'retraces': ds['retraces'],
            'fallbacks': ds['fallbacks'], 'cache_size': ds['cache_size']},
        'jit': {
            'compiles': int(reg.value('paddle_jit_compiles_total')),
            'compile_seconds': reg.value(
                'paddle_jit_compile_seconds_total'),
            'cache_entries': _jit_cache_entries(reg)},
        'collectives': {
            'calls': int(comm['calls']), 'bytes': int(comm['bytes']),
            'per_op': [{'op': op, 'axis': axis,
                        'calls': int(row['calls']),
                        'bytes': int(row['bytes'])}
                       for (op, axis), row
                       in sorted(comm['per_op'].items())[:max_rows]]},
        'offload': {
            'h2d_bytes': int(reg.value('paddle_offload_h2d_bytes_total')),
            'd2h_bytes': int(reg.value('paddle_offload_d2h_bytes_total'))},
        'steps': {
            'total': int(reg.value('paddle_steps_total')),
            'steps_per_sec': reg.value('paddle_steps_per_sec'),
            'tokens_per_sec': reg.value('paddle_tokens_per_sec'),
            'loss_last': reg.value('paddle_loss_last'),
            # trailing-window step-time percentiles off the train.step
            # span histogram (the windowed quantile sketch — no
            # Prometheus-side bucket math)
            'step_time_quantiles_ms': _span_quantiles_ms(
                reg, 'train.step') or _span_quantiles_ms(
                    reg, 'fleet.dist_train_step')
            or _span_quantiles_ms(reg, 'step.compute')},
        'memory': {
            'watermark_bytes': reg.value('paddle_memory_watermark_bytes')},
        'resilience': {
            'retries': int(_labeled_total(
                reg, 'paddle_resilience_retries_total')),
            'rollbacks': int(reg.value(
                'paddle_resilience_rollbacks_total')),
            'skipped_batches': int(reg.value(
                'paddle_resilience_skipped_batches_total')),
            'preempt_saves': int(reg.value(
                'paddle_resilience_preempt_saves_total')),
            'hangs': int(reg.value('paddle_resilience_hangs_total'))},
        'checkpoints': {
            'saves': int(reg.value('paddle_checkpoint_saves_total')),
            'save_bytes': int(reg.value(
                'paddle_checkpoint_save_bytes_total')),
            'restores': int(reg.value('paddle_checkpoint_restores_total')),
            'restore_bytes': int(reg.value(
                'paddle_checkpoint_restore_bytes_total'))},
        'serving': {
            'submitted': int(reg.value('paddle_serving_requests_total',
                                       status='submitted')),
            'completed': int(reg.value('paddle_serving_requests_total',
                                       status='completed')),
            'failed': int(reg.value('paddle_serving_requests_total',
                                    status='failed')),
            'queue_depth': int(reg.value('paddle_serving_queue_depth')),
            'active_slots': int(reg.value('paddle_serving_active_slots')),
            'slots': int(reg.value('paddle_serving_slots')),
            'tokens': int(reg.value('paddle_serving_tokens_total')),
            'ttft_avg_ms': _hist_avg_ms(reg, 'paddle_serving_ttft_seconds'),
            'tpot_avg_ms': _hist_avg_ms(reg, 'paddle_serving_tpot_seconds'),
            'ttft_quantiles_ms': _hist_quantiles_ms(
                reg, 'paddle_serving_ttft_seconds'),
            'tpot_quantiles_ms': _hist_quantiles_ms(
                reg, 'paddle_serving_tpot_seconds'),
            'prefills': int(_labeled_total(
                reg, 'paddle_serving_prefills_total')),
            'decode_steps': int(reg.value(
                'paddle_serving_decode_steps_total')),
            'prefix': {
                'hits': int(reg.value(
                    'paddle_serving_prefix_hits_total')),
                'misses': int(reg.value(
                    'paddle_serving_prefix_misses_total')),
                'tokens_reused': int(reg.value(
                    'paddle_serving_prefix_tokens_reused_total')),
                'retained_slots': int(reg.value(
                    'paddle_serving_prefix_retained_slots')),
                'evictions': int(reg.value(
                    'paddle_serving_prefix_evictions_total'))},
            'chunk': {
                'rounds': int(reg.value(
                    'paddle_serving_chunk_rounds_total')),
                'tokens': int(reg.value(
                    'paddle_serving_chunk_tokens_total'))},
            'spec': {
                'rounds': int(reg.value(
                    'paddle_serving_spec_rounds_total')),
                'proposed': int(reg.value(
                    'paddle_serving_spec_proposed_total')),
                'accepted': int(reg.value(
                    'paddle_serving_spec_accepted_total'))}},
        'router': _router_data(reg),
        'elastic': _elastic_data(reg),
        'goodput': _obs.get_ledger().report(),
        'roofline': _obs.roofline_summary(max_rows=max_rows),
        'programs': _obs.program_catalog().top_programs(n=max_rows),
        'program_store': _program_store_data(),
        'spans': span_rows,
        'events': {'logged': len(log), 'dropped': log.dropped,
                   'flight_dumps': int(_labeled_total(
                       reg, 'paddle_flight_dumps_total'))},
    }


def _program_store_data() -> dict:
    """Program-store view: tiers, warm/cold posture, cold-start wall
    time (the first-class availability number for restarts)."""
    try:
        from .programs import get_store
        return get_store().stats()
    except Exception:  # paddle-lint: disable=swallowed-exception -- summary section degrades to an explicit empty-store posture dict
        return {'persistent': False, 'dir': None, 'memory_entries': 0,
                'programs': 0, 'loaded_from_disk': 0, 'hits_memory': 0,
                'hits_disk': 0, 'misses': 0, 'rejects': 0,
                'persisted': 0, 'persist_skips': 0, 'invalidated': 0,
                'preload': None, 'coldstart_seconds': None,
                'disk_entries': 0,
                'donation': {'enabled': False, 'posture': 'off',
                             'verdict': None, 'reason': '',
                             'donated_entries': 0,
                             'sentinel_pending': 0}}


def _router_data(reg) -> dict:
    """Serving-router view: fleet counters + per-replica breaker state,
    load, and active degraded states (the /summary per-replica health)."""
    breaker_names = {0: 'closed', 1: 'half_open', 2: 'open'}
    per_replica = []
    fam = reg.get('paddle_router_breaker_state')
    out_fam = reg.get('paddle_router_outstanding_tokens')
    if fam is not None:
        for (rid,), child in sorted(fam.children()):
            outstanding = 0
            if out_fam is not None:
                oc = out_fam._children.get((rid,))
                outstanding = int(oc.value) if oc is not None else 0
            per_replica.append({
                'replica': rid,
                'breaker': breaker_names.get(int(child.value),
                                             str(child.value)),
                'outstanding_tokens': outstanding,
                'health_states': sorted(
                    _obs.degraded_states(scope=f'replica:{rid}')),
            })
    outcomes: dict = {}
    req_fam = reg.get('paddle_router_requests_total')
    if req_fam is not None:
        for (tenant, outcome), child in req_fam.children():
            outcomes[outcome] = outcomes.get(outcome, 0) + int(child.value)
    return {
        'replicas': int(reg.value('paddle_router_replicas')),
        'available': int(reg.value('paddle_router_available_replicas')),
        'queue_depth': int(reg.value('paddle_router_queue_depth')),
        'failovers': int(_labeled_total(
            reg, 'paddle_router_failovers_total')),
        'shed': int(_labeled_total(reg, 'paddle_router_shed_total')),
        'outcomes': outcomes,
        'per_replica': per_replica,
    }


def _elastic_data(reg) -> dict:
    """Elastic-training view: current mesh devices + the resize history
    every shrink/grow transition appends (fleet.rebuild_mesh)."""
    try:
        from .distributed import env, fleet
        history = fleet.resize_history()
        devices = int(env.get_mesh(auto_init=False).size) \
            if env.has_mesh() else 0
    except Exception:  # paddle-lint: disable=swallowed-exception -- summary section degrades to devices=0; report must render without a mesh
        history, devices = [], 0
    return {'devices': devices, 'resizes': len(history),
            'history': history}


def observability_summary(max_rows: int = 10, as_dict: bool = False):
    """One report over the single shared observability registry: where
    this process's time, bytes, and compiles went (upstream: stitched
    together by hand from paddle.profiler output + fleet worker logs).

    Sections always print (zeros included) so tooling can grep fields:
    dispatch hit-rate, jit compile count + seconds, per-(op, axis)
    collective calls/bytes, offload H2D/D2H transfer bytes, step/token
    throughput + last loss, device-memory watermark, serving engine
    traffic (requests/queue/slots/TTFT/TPOT), per-program XLA cost
    attribution (ProgramCatalog), and the hottest host spans.

    `as_dict=True` returns the machine-readable structure backing the
    text (the /summary?format=json payload); both views are rendered
    from the SAME snapshot so their headline counters always agree.
    """
    d = _observability_data(max_rows)
    if as_dict:
        return d
    ds, jit = d['dispatch'], d['jit']
    lines = [f'observability summary (process {d["process_index"]})',
             f'  dispatch: {ds["calls"]} calls  '
             f'hit_rate {ds["hit_rate"]:.1%}  ({ds["misses"]} misses, '
             f'{ds["retraces"]} retraces, {ds["fallbacks"]} fallbacks, '
             f'cache_size {ds["cache_size"]})',
             f'  jit: {jit["compiles"]} compiles  '
             f'{jit["compile_seconds"]:.3f} s '
             f'compile time  cache entries: {jit["cache_entries"]}']
    comm = d['collectives']
    lines.append(f'  collectives: {comm["calls"]} calls  '
                 f'{comm["bytes"]} bytes')
    for row in comm['per_op']:
        lines.append(f'    {row["op"]:<16} axis={row["axis"]:<6} '
                     f'{row["calls"]:>6} calls {row["bytes"]:>12} '
                     f'bytes')
    lines.append(
        f'  offload: {d["offload"]["h2d_bytes"]} H2D bytes  '
        f'{d["offload"]["d2h_bytes"]} D2H bytes')
    st = d['steps']
    lines.append(
        f'  steps: {st["total"]} total  '
        f'{st["steps_per_sec"]:.2f} steps/s  '
        f'{st["tokens_per_sec"]:.1f} tokens/s  '
        f'loss {st["loss_last"]:.4f}')
    if st['step_time_quantiles_ms']:
        qs = st['step_time_quantiles_ms']
        lines.append('    step time ' + '  '.join(
            f'p{float(q) * 100:g} {v:.2f} ms' for q, v in sorted(
                qs.items(), key=lambda kv: float(kv[0]))))
    lines.append(
        f'  memory: watermark '
        f'{d["memory"]["watermark_bytes"] / 2**20:.1f} MiB')
    rs = d['resilience']
    lines.append(
        f'  resilience: {rs["retries"]} retries  '
        f'{rs["rollbacks"]} rollbacks  '
        f'{rs["skipped_batches"]} skipped batches  '
        f'{rs["preempt_saves"]} preempt saves  '
        f'{rs["hangs"]} hangs')
    ck = d['checkpoints']
    lines.append(
        f'  checkpoints: {ck["saves"]} saves ({ck["save_bytes"]} bytes)  '
        f'{ck["restores"]} restores ({ck["restore_bytes"]} bytes)')
    sv = d['serving']
    lines.append(
        f'  serving: {sv["submitted"]} requests '
        f'({sv["completed"]} done, {sv["failed"]} failed)  '
        f'queue {sv["queue_depth"]}  '
        f'slots {sv["active_slots"]}/{sv["slots"]}  '
        f'{sv["tokens"]} tokens')
    lines.append(
        f'    ttft avg {sv["ttft_avg_ms"]:.2f} ms  '
        f'tpot avg {sv["tpot_avg_ms"]:.2f} ms  '
        f'{sv["prefills"]} prefills  '
        f'{sv["decode_steps"]} decode steps')
    if sv['ttft_quantiles_ms']:
        ttft_q = '  '.join(f'p{float(q) * 100:g} {v:.2f}'
                           for q, v in sorted(
                               sv['ttft_quantiles_ms'].items(),
                               key=lambda kv: float(kv[0])))
        tpot_q = '  '.join(f'p{float(q) * 100:g} {v:.2f}'
                           for q, v in sorted(
                               sv['tpot_quantiles_ms'].items(),
                               key=lambda kv: float(kv[0])))
        lines.append(f'    ttft ms: {ttft_q}'
                     + (f'  |  tpot ms: {tpot_q}' if tpot_q else ''))
    px, chk, spc = sv['prefix'], sv['chunk'], sv['spec']
    hit_rate = (px['hits'] / (px['hits'] + px['misses'])
                if px['hits'] + px['misses'] else 0.0)
    lines.append(
        f'    prefix cache: {px["hits"]} hits / {px["misses"]} misses '
        f'({hit_rate:.1%})  {px["tokens_reused"]} tokens reused  '
        f'{px["retained_slots"]} retained  {px["evictions"]} evicted')
    spec_rate = (spc['accepted'] / spc['proposed']
                 if spc['proposed'] else 0.0)
    lines.append(
        f'    chunked prefill: {chk["rounds"]} rounds '
        f'{chk["tokens"]} tokens  |  speculation: {spc["rounds"]} '
        f'rounds  accept {spc["accepted"]}/{spc["proposed"]} '
        f'({spec_rate:.1%})')
    rt = d['router']
    lines.append(
        f'  router: {rt["replicas"]} replicas '
        f'({rt["available"]} available)  queue {rt["queue_depth"]}  '
        f'{rt["failovers"]} failovers  {rt["shed"]} shed')
    for row in rt['per_replica']:
        states = ','.join(row['health_states']) or 'healthy'
        lines.append(
            f'    replica {row["replica"]}: breaker {row["breaker"]}  '
            f'{states}  outstanding {row["outstanding_tokens"]} tokens')
    el = d['elastic']
    lines.append(f'  elastic: {el["devices"]} devices  '
                 f'{el["resizes"]} resizes')
    for h in el['history'][-max_rows:]:
        lines.append(
            f'    {h["kind"]:<7} {h["from_devices"]}->{h["to_devices"]} '
            f'devices  mesh {h["to"]}  ({h["reason"]})')
    gp = d['goodput']
    lines.append(
        f'  goodput: {gp["wall_seconds"]:.1f} s wall  '
        f'{gp["attributed_seconds"]:.1f} s attributed  '
        f'residual {gp["fractions"]["residual"]:.1%}'
        + (f'  (+{gp["overcount_seconds"]:.1f} s concurrent overcount)'
           if gp['overcount_seconds'] > 0 else ''))
    for cat, secs in gp['categories'].items():
        if secs > 0:
            lines.append(f'    {cat:<20}{secs:>10.3f} s '
                         f'{gp["fractions"][cat]:>7.1%}')
    rf = d['roofline']
    if rf['mfu'] is not None:
        lines.append(
            f'  roofline: MFU {rf["mfu"]:.3f} on {rf["device_kind"]} '
            f'(peak {rf["peak_flops"] / 1e12:.0f} TFLOP/s, '
            f'{rf["source"]})  '
            f'{rf["bound_counts"]["compute"]} compute-bound / '
            f'{rf["bound_counts"]["bandwidth"]} bandwidth-bound '
            f'programs')
        for row in rf['programs']:
            bound = row['bound'] or '?'
            lines.append(f'    {row["name"][:31]:<32} mfu '
                         f'{row["mfu"]:.3f}  {bound}-bound  '
                         f'{row["host_seconds"]:.3f} s')
    else:
        lines.append(
            f'  roofline: MFU unknown (device {rf["device_kind"]!r} '
            f'not in the peak table; set PADDLE_PEAK_FLOPS / '
            f'PADDLE_PEAK_HBM_GBPS)')
    ps = d['program_store']
    tier = (f'persistent @ {ps["dir"]}' if ps['persistent']
            else 'memory-only')
    lines.append(
        f'  program store: {tier}  {ps["memory_entries"]} resident '
        f'({ps["loaded_from_disk"]} warm-loaded)  '
        f'hits {ps["hits_memory"]}m/{ps["hits_disk"]}d  '
        f'misses {ps["misses"]}  rejects {ps["rejects"]}')
    if ps.get('coldstart_seconds') is not None:
        pl = ps.get('preload') or {}
        lines.append(
            f'    cold start: warm at {ps["coldstart_seconds"]:.3f}s '
            f'(preload {pl.get("loaded", 0)} programs in '
            f'{pl.get("seconds", 0.0):.3f}s, '
            f'{pl.get("rejected", 0)} rejected)')
    dn = ps.get('donation') or {}
    if dn:
        extra = ''
        if dn.get('posture') == 'on':
            extra = (f'  donated {dn.get("donated_entries", 0)} '
                     f'resident, sentinel {dn.get("sentinel_pending", 0)}'
                     f' pending')
        elif dn.get('reason'):
            extra = f'  ({dn["reason"]})'
        lines.append(
            f'    donation: {dn.get("posture", "off")}'
            f'{" [" + str(dn.get("verdict")) + "]" if dn.get("verdict") else ""}'
            f'{extra}')
    lines.append(f'  programs: {len(d["programs"])} tracked '
                 f'(top by host time)')
    for p in d['programs']:
        lines.append(
            f'    {p["name"][:31]:<32} {p["invocations"]:>6} calls '
            f'{p["host_seconds"]:>9.3f} s  '
            f'{p["flops"] / 1e9:>9.3f} GFLOP  '
            f'{p["bytes_accessed"] / 1e9:>8.3f} GB  '
            f'peak {p["peak_memory_bytes"] / 2**20:>8.1f} MiB')
    lines.append(f'  host spans: {len(d["spans"])} region(s), '
                 f'event log {d["events"]["logged"]} events '
                 f'({d["events"]["dropped"]} dropped, '
                 f'{d["events"]["flight_dumps"]} flight dumps)')
    for row in d['spans']:
        lines.append(f'    {row["name"]:<32} {row["calls"]:>6} calls '
                     f'{row["total_s"]:>10.4f} s  avg '
                     f'{row["avg_ms"]:>8.2f} ms')
    return '\n'.join(lines)


def _jit_cache_entries(reg) -> int:
    fam = reg.get('paddle_jit_cache_entries')
    if fam is None:
        return 0
    return int(fam.total())


def _labeled_total(reg, name: str) -> float:
    """Sum a labeled counter family across all label values."""
    fam = reg.get(name)
    if fam is None:
        return 0.0
    return fam.total()


def _hist_avg_ms(reg, name: str) -> float:
    """Mean of an unlabeled histogram family, in milliseconds."""
    fam = reg.get(name)
    if fam is None:
        return 0.0
    child = fam._children.get(())
    if child is None or not child.count:
        return 0.0
    return child.sum / child.count * 1e3


def _hist_quantiles_ms(reg, name: str) -> dict:
    """Windowed p50/p95/p99 of an unlabeled histogram, in ms."""
    fam = reg.get(name)
    if fam is None:
        return {}
    child = fam._children.get(())
    if child is None:
        return {}
    return {q: v * 1e3 for q, v in child.window_quantiles().items()}


def _span_quantiles_ms(reg, span_name: str) -> dict:
    """Windowed quantiles of one `paddle_span_seconds{name=}` child."""
    fam = reg.get('paddle_span_seconds')
    if fam is None:
        return {}
    child = fam._children.get((span_name,))
    if child is None:
        return {}
    return {q: v * 1e3 for q, v in child.window_quantiles().items()}


class LossSpikeDetector:
    """Windowed spike detector: flags a step whose loss exceeds
    mean + k*std of the trailing window, or is non-finite.

    Flagged values are EXCLUDED from the trailing window — a spike (or a
    level shift that registers as one) must not inflate its own baseline
    mean/std, which would mask every subsequent spike. Each flagged step
    also emits a `loss_spike` event into the observability EventLog."""

    def __init__(self, window: int = 20, threshold_sigma: float = 6.0,
                 min_steps: int = 5):
        self.window: Deque[float] = collections.deque(maxlen=window)
        self.k = threshold_sigma
        self.min_steps = min_steps
        self.spikes: List[int] = []
        self._step = 0

    def _note_spike(self, value: float):
        self.spikes.append(self._step)
        _obs.emit('loss_spike', step=self._step, loss=value,
                  window=len(self.window))

    def update(self, loss: float) -> bool:
        """Returns True if this step is a spike."""
        v = float(loss)
        self._step += 1
        if not math.isfinite(v):
            self._note_spike(v)
            return True
        spiked = False
        if len(self.window) >= self.min_steps:
            mean = sum(self.window) / len(self.window)
            var = sum((x - mean) ** 2 for x in self.window) \
                / len(self.window)
            std = math.sqrt(var)
            if v > mean + self.k * max(std, 1e-12):
                spiked = True
                self._note_spike(v)
        if not spiked:
            self.window.append(v)
        return spiked
