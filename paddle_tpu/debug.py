"""Failure detection (upstream: paddle.amp.debugging / check_nan_inf,
python/paddle/amp/debugging.py + the fleet loss-spike monitor).

- `check_numerics(x, name)` — raises on NaN/Inf in eager mode; under
  jit it routes through `jax.debug` safe-guarding via checkify-style
  host callback only when enabled (zero overhead when off).
- `enable_check_numerics()` — installs a tape-level hook: every op
  recorded by apply_op is scanned for non-finite outputs (eager only,
  the DyGraph debugging workflow).
- `LossSpikeDetector` — windowed z-score monitor used by hapi/fleet to
  flag divergence (upstream: loss scaling skip-counters + spike logs).
"""
from __future__ import annotations

import collections
import math
from typing import Deque, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import flags as _flags
from .tensor import Tensor


class NumericsError(RuntimeError):
    pass


def check_numerics(x, name: str = 'tensor', raise_on_error: bool = True):
    """Assert a tensor is finite. Eager: host check with a precise count.
    Traced: uses jax.debug.callback so the check travels into the XLA
    program (no effect on the computed value)."""
    val = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    if not jnp.issubdtype(val.dtype, jnp.floating):
        return x

    if isinstance(val, jax.core.Tracer):
        def cb(n_nan, n_inf):
            if int(n_nan) or int(n_inf):
                msg = (f'check_numerics({name}): {int(n_nan)} NaN, '
                       f'{int(n_inf)} Inf values')
                if raise_on_error:
                    raise NumericsError(msg)
                print(msg)
        f32 = val.astype(jnp.float32)
        jax.debug.callback(cb, jnp.isnan(f32).sum(),
                           jnp.isinf(f32).sum())
        return x

    f32 = np.asarray(val, np.float32)
    n_nan = int(np.isnan(f32).sum())
    n_inf = int(np.isinf(f32).sum())
    if n_nan or n_inf:
        msg = (f'check_numerics({name}): {n_nan} NaN, {n_inf} Inf of '
               f'{f32.size} values, shape {tuple(f32.shape)}')
        if raise_on_error:
            raise NumericsError(msg)
        print(msg)
    return x


# ---------------------------------------------------------------------------
# tape-level monitor (FLAGS_check_nan_inf)
# ---------------------------------------------------------------------------

def _scan_outputs(out, op_name):
    def scan(t):
        if isinstance(t, Tensor) and not isinstance(
                t.value, jax.core.Tracer):
            check_numerics(t, name=op_name or 'op')
        return t
    jax.tree_util.tree_map(scan, out,
                           is_leaf=lambda v: isinstance(v, Tensor))


def enable_check_numerics(level: int = 0):
    """Scan every eager op output for NaN/Inf via the apply_op hook
    (upstream: FLAGS_check_nan_inf=1). Heavy — debugging only."""
    from . import tensor as tmod
    _flags.set_flags({'FLAGS_check_nan_inf': True,
                      'FLAGS_check_nan_inf_level': level})
    tmod._numerics_hook = _scan_outputs


def disable_check_numerics():
    from . import tensor as tmod
    _flags.set_flags({'FLAGS_check_nan_inf': False})
    tmod._numerics_hook = None


class LossSpikeDetector:
    """Windowed spike detector: flags a step whose loss exceeds
    mean + k*std of the trailing window, or is non-finite."""

    def __init__(self, window: int = 20, threshold_sigma: float = 6.0,
                 min_steps: int = 5):
        self.window: Deque[float] = collections.deque(maxlen=window)
        self.k = threshold_sigma
        self.min_steps = min_steps
        self.spikes: List[int] = []
        self._step = 0

    def update(self, loss: float) -> bool:
        """Returns True if this step is a spike."""
        v = float(loss)
        self._step += 1
        if not math.isfinite(v):
            self.spikes.append(self._step)
            return True
        spiked = False
        if len(self.window) >= self.min_steps:
            mean = sum(self.window) / len(self.window)
            var = sum((x - mean) ** 2 for x in self.window) \
                / len(self.window)
            std = math.sqrt(var)
            if v > mean + self.k * max(std, 1e-12):
                spiked = True
                self.spikes.append(self._step)
        self.window.append(v)
        return spiked
