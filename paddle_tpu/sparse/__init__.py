"""paddle.sparse compatibility layer (upstream: python/paddle/sparse/ —
SparseCooTensor/SparseCsrTensor creation, conversion, unary/binary ops,
matmul).

TPU-native design: COO tensors wrap `jax.experimental.sparse.BCOO`
(XLA-lowerable batched-COO — the only sparse format with a real XLA
lowering path); CSR keeps paddle's (crows, cols, values) surface and
converts to BCOO for compute. Dense<->sparse conversions and
`sparse.matmul` against dense operands run on device; elementwise
binaries require matching sparsity patterns (documented upstream
behavior for same-shape COO inputs after coalesce)."""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as _jsparse

from ..tensor import Tensor, to_jax

__all__ = [
    'sparse_coo_tensor', 'sparse_csr_tensor', 'SparseCooTensor',
    'SparseCsrTensor', 'is_same_shape', 'add', 'subtract', 'multiply',
    'matmul', 'masked_matmul', 'relu', 'tanh', 'sqrt', 'sin', 'abs',
    'neg', 'pow', 'cast', 'transpose', 'nn',
]


_INDEX_BOUND = 2 ** 31 - 1  # int32 index space; x64 is off globally


def _check_index_bound(shape):
    if any(int(s) > _INDEX_BOUND for s in shape):
        raise ValueError(
            f'sparse indices are int32; dimension sizes {tuple(shape)} '
            f'exceed {_INDEX_BOUND}')


class SparseCooTensor:
    """COO sparse tensor over BCOO; `indices` follows paddle's
    [sparse_ndim, nnz] layout (BCOO stores [nnz, ndim] internally)."""

    is_sparse_coo_val = True

    def __init__(self, bcoo: _jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle surface -----------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.data.dtype

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def coalesce(self) -> 'SparseCooTensor':
        return SparseCooTensor(
            _jsparse.bcoo_sum_duplicates(self._bcoo))

    def to_sparse_csr(self) -> 'SparseCsrTensor':
        if len(self.shape) != 2:
            raise ValueError('to_sparse_csr supports 2-D tensors only')
        _check_index_bound(self.shape)
        coo = _jsparse.bcoo_sum_duplicates(self._bcoo)
        rows, cols = coo.indices[:, 0], coo.indices[:, 1]
        order = jnp.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], coo.data[order]
        n_rows = self.shape[0]
        # int32 indices by design (x64 is off globally): TPU-friendly and
        # enough for nnz / dims < 2**31 — the _INDEX_BOUND guard below
        crows = jnp.zeros(n_rows + 1, jnp.int32).at[rows + 1].add(1)
        return SparseCsrTensor(jnp.cumsum(crows), cols, vals, self.shape)

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def astype(self, dtype) -> 'SparseCooTensor':
        return SparseCooTensor(_jsparse.BCOO(
            (self._bcoo.data.astype(jnp.dtype(dtype)), self._bcoo.indices),
            shape=self._bcoo.shape))

    def t(self) -> 'SparseCooTensor':
        return transpose(self, list(range(len(self.shape)))[::-1])

    def numpy(self) -> np.ndarray:
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (f'SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, '
                f'dtype={self.dtype})')

    def _unary(self, fn) -> 'SparseCooTensor':
        return SparseCooTensor(_jsparse.BCOO(
            (fn(self._bcoo.data), self._bcoo.indices),
            shape=self._bcoo.shape))

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """CSR surface (crows/cols/values); compute converts to BCOO."""

    def __init__(self, crows, cols, values, shape: Sequence[int]):
        self._crows = jnp.asarray(crows)
        self._cols = jnp.asarray(cols)
        self._values = jnp.asarray(values)
        self._shape = list(int(s) for s in shape)

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return Tensor(self._values)

    def _rows(self):
        counts = jnp.diff(self._crows)
        return jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz())

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        idx = jnp.stack([self._rows(), self._cols], axis=1)
        return SparseCooTensor(_jsparse.BCOO((self._values, idx),
                                             shape=tuple(self._shape)))

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense().value)

    def __repr__(self):
        return (f'SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, '
                f'dtype={self.dtype})')


def _as_bcoo(x) -> _jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()._bcoo
    raise TypeError(f'expected a sparse tensor, got {type(x).__name__}')


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """Build a COO tensor from paddle-layout [sparse_ndim, nnz] indices."""
    idx = jnp.asarray(to_jax(indices), jnp.int32).T
    vals = jnp.asarray(to_jax(values))
    if dtype is not None:
        vals = vals.astype(jnp.dtype(dtype))
    if shape is None:
        shape = tuple(int(s) for s in (idx.max(axis=0) + 1))
    _check_index_bound(shape)
    return SparseCooTensor(
        _jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    vals = jnp.asarray(to_jax(values))
    if dtype is not None:
        vals = vals.astype(jnp.dtype(dtype))
    _check_index_bound(shape)
    return SparseCsrTensor(jnp.asarray(to_jax(crows), jnp.int32),
                           jnp.asarray(to_jax(cols), jnp.int32),
                           vals, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# -- binary ops -------------------------------------------------------------

def _binary(x, y, fn):
    a, b = _as_bcoo(x), _as_bcoo(y)
    if a.shape != b.shape:
        raise ValueError(f'shape mismatch: {a.shape} vs {b.shape}')
    a = _jsparse.bcoo_sum_duplicates(a)
    b = _jsparse.bcoo_sum_duplicates(b)
    # union of patterns via concat + sum_duplicates on transformed values
    return SparseCooTensor(_jsparse.bcoo_sum_duplicates(_jsparse.BCOO(
        (jnp.concatenate([a.data,
                          -b.data if fn == 'sub' else b.data]),
         jnp.concatenate([a.indices, b.indices])), shape=a.shape)))


def add(x, y) -> SparseCooTensor:
    return _binary(x, y, 'add')


def subtract(x, y) -> SparseCooTensor:
    return _binary(x, y, 'sub')


def multiply(x, y):
    """Elementwise product. Sparse*scalar scales values; sparse*sparse
    multiplies via the dense intersection (patterns need not match)."""
    if isinstance(y, (int, float)):
        x_ = _as_bcoo(x)
        return SparseCooTensor(_jsparse.BCOO(
            (x_.data * y, x_.indices), shape=x_.shape))
    a = _jsparse.bcoo_sum_duplicates(_as_bcoo(x))
    b_dense = _as_bcoo(y).todense()
    gathered = b_dense[tuple(a.indices[:, i]
                             for i in range(a.indices.shape[1]))]
    return SparseCooTensor(_jsparse.BCOO(
        (a.data * gathered, a.indices), shape=a.shape))


def matmul(x, y) -> Tensor:
    """sparse @ dense -> dense (upstream sparse.matmul); rides XLA's
    BCOO dot_general lowering (gather + segment-sum on TPU)."""
    yv = y.value if isinstance(y, Tensor) else jnp.asarray(to_jax(y))
    return Tensor(_as_bcoo(x) @ yv)


def masked_matmul(x: Tensor, y: Tensor, mask) -> SparseCooTensor:
    """dense @ dense sampled at `mask`'s sparsity (SDDMM)."""
    m = _jsparse.bcoo_sum_duplicates(_as_bcoo(mask))
    xv = x.value if isinstance(x, Tensor) else jnp.asarray(to_jax(x))
    yv = y.value if isinstance(y, Tensor) else jnp.asarray(to_jax(y))
    rows, cols = m.indices[:, 0], m.indices[:, 1]
    vals = jnp.einsum('nk,nk->n', xv[rows], yv.T[cols])
    return SparseCooTensor(_jsparse.BCOO((vals, m.indices), shape=m.shape))


# -- unary ops --------------------------------------------------------------

def _make_unary(fn, name):
    def op(x):
        if isinstance(x, SparseCsrTensor):
            coo = op(x.to_sparse_coo())
            return coo.to_sparse_csr()
        return x._unary(fn)
    op.__name__ = name
    return op


relu = _make_unary(lambda v: jnp.maximum(v, 0), 'relu')
tanh = _make_unary(jnp.tanh, 'tanh')
sqrt = _make_unary(jnp.sqrt, 'sqrt')
sin = _make_unary(jnp.sin, 'sin')
abs = _make_unary(jnp.abs, 'abs')
neg = _make_unary(jnp.negative, 'neg')


def pow(x, factor):
    return _make_unary(lambda v: jnp.power(v, factor), 'pow')(x)


def cast(x, index_dtype=None, value_dtype=None):
    b = _as_bcoo(x)
    idx = b.indices.astype(jnp.dtype(index_dtype)) if index_dtype else \
        b.indices
    vals = b.data.astype(jnp.dtype(value_dtype)) if value_dtype else b.data
    return SparseCooTensor(_jsparse.BCOO((vals, idx), shape=b.shape))


def transpose(x, perm) -> SparseCooTensor:
    b = _as_bcoo(x)
    return SparseCooTensor(_jsparse.bcoo_transpose(b, permutation=perm))


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


nn = type('nn', (), {'ReLU': _SparseReLU})
