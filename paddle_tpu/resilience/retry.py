"""Transient-error retry: classifier + exponential backoff with jitter.

Real pod runs shed a steady drizzle of *transient* failures — PjRt RPC
drops, RESOURCE_EXHAUSTED from a neighbour's temporary HBM/host-RAM
pressure, compile-service timeouts — that a production trainer must
absorb without operator involvement, while *fatal* errors (shape
mismatches, assertion failures, real OOM loops) must still surface
immediately. This module is the one place that judgment lives:

- `is_transient(exc)` — the error classifier. Type-based first
  (ConnectionError/TimeoutError/`TransientError`), then message-based
  against the PjRt/absl status vocabulary (RESOURCE_EXHAUSTED,
  UNAVAILABLE, DEADLINE_EXCEEDED, ...). Extendable at runtime via
  `register_transient` (deployment-specific storage clients, fault
  injection in tests).
- `RetryPolicy` — max_retries / exponential backoff / jitter knobs,
  defaulting from the FLAGS_ft_* registry.
- `retry(policy, site)` decorator and `call_with_retry(fn, ...)` — the
  wrappers applied to checkpoint I/O, collective-wrapped train steps,
  and device transfers. Every retry increments
  `paddle_resilience_retries_total{site}` and emits a `retry` event so
  `debug.observability_summary()` shows recovery activity.
"""
from __future__ import annotations

import functools
import random
import socket
import time
from typing import Any, Callable, Optional, Tuple, Type

from .. import flags as _flags
from .. import observability as _obs


class TransientError(RuntimeError):
    """Marker exception that is always classified as retryable (used by
    tests and as a base class for custom transient failures)."""


class FatalError(RuntimeError):
    """Marker exception that is never retried, even if its message
    matches a transient pattern."""


# absl/PjRt status vocabulary + the usual socket-level suspects. Matched
# case-insensitively against "TypeName: message".
_TRANSIENT_MARKERS: Tuple[str, ...] = (
    'resource_exhausted',
    'resource exhausted',
    'deadline_exceeded',
    'deadline exceeded',
    'unavailable',
    'aborted',
    'cancelled',
    'data_loss',
    'connection reset',
    'connection refused',
    'connection closed',
    'broken pipe',
    'temporarily unavailable',
    'try again',
    'socket closed',
    'transport closed',
    'compile timeout',
    'compilation timed out',
    'preempted',
    # replica RPC vocabulary (serving/remote.py): a peer dying
    # mid-frame or a corrupted stream reads exactly like a device-side
    # UNAVAILABLE — evict, resubmit to survivors, maybe retry
    'incomplete frame',
    'frame sha256 mismatch',
    'connection aborted',
    'timed out',
)

# ConnectionResetError / BrokenPipeError / ConnectionRefusedError /
# ConnectionAbortedError are ConnectionError subclasses and
# socket.timeout aliases TimeoutError since 3.10, but the fleet-runtime
# failover contract depends on every one of them classifying transient,
# so they are listed EXPLICITLY — subclass-lattice drift in a future
# stdlib must not silently change failover behavior (chain-walk tests
# pin each name).
_transient_types: Tuple[Type[BaseException], ...] = (
    TransientError, ConnectionError, TimeoutError, InterruptedError,
    ConnectionResetError, BrokenPipeError, ConnectionRefusedError,
    ConnectionAbortedError, socket.timeout,
)


def register_transient(exc_type: Type[BaseException]):
    """Teach the classifier a new retryable exception type (e.g. a cloud
    storage client's throttling error, or a test's injected fault)."""
    global _transient_types
    if exc_type not in _transient_types:
        _transient_types = _transient_types + (exc_type,)
    return exc_type


# chain walk bounds: real chains are 2-3 deep; the cap guards against a
# pathological graph (and the id-set against __context__ cycles)
_CHAIN_LIMIT = 16

_NEVER_TRANSIENT = (KeyboardInterrupt, SystemExit, GeneratorExit)
_PROGRAMMING_ERRORS = (AssertionError, TypeError, ValueError, KeyError,
                       AttributeError, NotImplementedError)


def exception_chain(exc: BaseException):
    """Yield `exc` and its `__cause__`/`__context__` ancestry, outermost
    first. Follows `raise X from Y` (`__cause__`) when explicit,
    otherwise the implicit `__context__` unless suppressed
    (`raise X from None`). Cycle-safe and depth-bounded."""
    seen = set()
    depth = 0
    while (exc is not None and id(exc) not in seen
           and depth < _CHAIN_LIMIT):
        seen.add(id(exc))
        depth += 1
        yield exc
        if exc.__cause__ is not None:
            exc = exc.__cause__
        elif not exc.__suppress_context__:
            exc = exc.__context__
        else:
            exc = None


def is_transient(exc: BaseException) -> bool:
    """True if `exc` looks like a failure that a bounded retry can
    outlive. Walks the `__cause__`/`__context__` chain: a transient PjRt
    error wrapped in a framework exception (the serving router's
    resubmission path raises `ReplicaFailure ... from the device error`)
    is still classified transient, while a FatalError anywhere in the
    chain — or fatal-by-construction errors (KeyboardInterrupt,
    programming errors) at the top — poisons the whole chain."""
    for e in exception_chain(exc):
        if isinstance(e, (FatalError,) + _NEVER_TRANSIENT):
            return False
    for e in exception_chain(exc):
        if isinstance(e, _PROGRAMMING_ERRORS):
            continue   # a caller bug never matches, even by message
        if isinstance(e, _transient_types):
            return True
        msg = f'{type(e).__name__}: {e}'.lower()
        if any(marker in msg for marker in _TRANSIENT_MARKERS):
            return True
    return False


class RetryPolicy:
    """Exponential backoff with +/- jitter over a transient classifier.

    max_retries counts *re*-attempts: max_retries=3 means up to 4 calls.
    delay(attempt) = min(base * multiplier**attempt, max_delay), scaled
    by a uniform factor in [1 - jitter, 1 + jitter] so a fleet of hosts
    retrying the same shared service doesn't stampede in lockstep.
    Defaults come from the FLAGS_ft_* registry; `classify` overrides the
    transient/fatal judgment per call site.
    """

    def __init__(self, max_retries: Optional[int] = None,
                 base_delay: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 multiplier: float = 2.0, jitter: float = 0.25,
                 classify: Optional[Callable[[BaseException], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_retries = int(_flags.flag('FLAGS_ft_max_retries')
                               if max_retries is None else max_retries)
        self.base_delay = float(_flags.flag('FLAGS_ft_retry_base_delay')
                                if base_delay is None else base_delay)
        self.max_delay = float(_flags.flag('FLAGS_ft_retry_max_delay')
                               if max_delay is None else max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.classify = classify or is_transient
        self.sleep = sleep

    def delay(self, attempt: int) -> float:
        """Backoff before re-attempt number `attempt` (0-based)."""
        d = min(self.base_delay * self.multiplier ** attempt,
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(d, 0.0)

    def retryable(self, exc: BaseException) -> bool:
        return self.classify(exc)


def _note_retry(site: str, attempt: int, exc: BaseException, delay: float):
    if not _obs.enabled():
        return
    _obs.get_registry().counter(
        'paddle_resilience_retries_total',
        'transient-error retries by call site',
        ('site',)).labels(site=site).inc()
    _obs.emit('retry', site=site, attempt=attempt,
              error=type(exc).__name__, delay_s=round(delay, 4))


def call_with_retry(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
                    site: str = 'generic', **kwargs) -> Any:
    """Run `fn(*args, **kwargs)`, re-attempting transient failures per
    `policy`. Fatal errors and exhausted budgets re-raise the original
    exception unchanged."""
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as exc:
            if attempt >= policy.max_retries or not policy.retryable(exc):
                raise
            d = policy.delay(attempt)
            _note_retry(site, attempt, exc, d)
            if d > 0:
                # spanned so the goodput ledger books backoff sleeps as
                # `retry_backoff`, not unattributed residual
                with _obs.span('resilience.backoff', site=site,
                               attempt=attempt):
                    policy.sleep(d)
            attempt += 1


def retry(policy: Optional[RetryPolicy] = None, site: Optional[str] = None):
    """Decorator form: `@retry(RetryPolicy(max_retries=5), site='io')`.
    Also usable bare (`@retry()`) with flag-default policy; `site`
    defaults to the function name for counter labeling."""
    def deco(fn):
        label = site or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call_with_retry(fn, *args, policy=policy, site=label,
                                   **kwargs)
        return wrapper
    return deco
