"""Step watchdog: detect hung steps (deadlocked collective, wedged
host callback, dead RPC tunnel) that neither raise nor return.

A hang is the failure mode retries and NaN checks cannot see — the step
simply never comes back. The watchdog is a daemon thread holding a
deadline; each step arms it on entry and disarms on return. If a step
overruns its deadline the watchdog fires ONCE for that step: it emits a
`hang_suspected` event carrying the last-known span from the EventLog
(the best available "where were we" without a debugger), bumps
`paddle_resilience_hangs_total`, and then runs the configured abort
action — `None` (observe only), `'interrupt'` (raise KeyboardInterrupt
in the main thread so the preemption path can checkpoint and exit), or
any callable.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional, Union

from .. import flags as _flags
from .. import observability as _obs
from ..analysis.runtime import concurrency as _concurrency


class StepWatchdog:
    """Thread-based deadline monitor for step execution.

    Args:
        deadline_s: seconds a single step may run before it is declared
            hang-suspected (default FLAGS_ft_step_deadline_s; <= 0
            disables the watchdog entirely).
        on_hang: None (event only), 'interrupt' (interrupt_main), or a
            callable(elapsed_seconds).
        poll_interval: check cadence; defaults to deadline / 4 capped to
            [10 ms, 1 s].
    """

    def __init__(self, deadline_s: Optional[float] = None,
                 on_hang: Union[None, str, Callable] = None,
                 poll_interval: Optional[float] = None):
        self.deadline = float(_flags.flag('FLAGS_ft_step_deadline_s')
                              if deadline_s is None else deadline_s)
        self.on_hang = on_hang
        self.poll = poll_interval if poll_interval is not None else \
            min(max(self.deadline / 4.0, 0.01), 1.0)
        self._lock = _concurrency.Lock('StepWatchdog._lock')
        self._armed_at: Optional[float] = None
        self._fired_this_arm = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = 0

    @property
    def enabled(self) -> bool:
        return self.deadline > 0

    def start(self) -> 'StepWatchdog':
        if not self.enabled:
            return self
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name='paddle-step-watchdog', daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def arm(self):
        with self._lock:
            self._armed_at = time.monotonic()
            self._fired_this_arm = False

    def disarm(self):
        with self._lock:
            fired = self._fired_this_arm
            self._armed_at = None
        if fired:
            # the step finally came back: /healthz recovers to 200
            from ..observability.server import clear_hang
            clear_hang(id(self))

    @contextlib.contextmanager
    def watch(self):
        """Bracket one step: arm on entry, disarm on exit (lazy-starts
        the monitor thread)."""
        if not self.enabled:
            yield
            return
        self.start()
        self.arm()
        try:
            yield
        finally:
            self.disarm()

    # -- monitor thread -----------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll):
            with self._lock:
                armed_at = self._armed_at
                already = self._fired_this_arm
            if armed_at is None or already:
                continue
            elapsed = time.monotonic() - armed_at
            if elapsed >= self.deadline:
                with self._lock:
                    self._fired_this_arm = True
                self._fire(elapsed)

    def _last_span(self) -> str:
        events = _obs.get_event_log().events()
        return events[-1].get('name', '?') if events else ''

    def _fire(self, elapsed: float):
        self.fired += 1
        # /healthz goes 503 until the hung step returns (disarm); the
        # hang_suspected event below also triggers a flight-recorder dump
        from ..observability.server import note_hang
        note_hang(id(self), {'elapsed_s': round(elapsed, 3),
                             'deadline_s': self.deadline,
                             'last_span': self._last_span()})
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_resilience_hangs_total',
                'steps that overran the watchdog deadline').inc()
            _obs.emit('hang_suspected', elapsed_s=round(elapsed, 3),
                      deadline_s=self.deadline, last_span=self._last_span())
        if self.on_hang == 'interrupt':
            import _thread
            _thread.interrupt_main()
        elif callable(self.on_hang):
            self.on_hang(elapsed)
