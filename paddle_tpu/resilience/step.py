"""FaultTolerantStep — rollback + skip-the-bad-batch around a train step.

Large-model practice (PaLM's skip-the-bad-step restarts) treats an
occasional NaN/Inf or loss-spike step as data to be dropped, not a run
to be killed: restore the last known-good state, skip the offending
batch, keep going — up to a bounded skip budget, past which something is
structurally wrong and the run must fail loudly.

The wrapper works over any step object shaped like `jit.TrainStep` /
`fleet.DistTrainStep` (callable(inputs, labels) -> loss, with `.layer`,
`._opt_state`, `._n_calls`), or over a bare callable given explicit
`snapshot_fn`/`restore_fn`. Snapshots are host-side numpy copies of
params/buffers/opt-state plus the step's RNG counter, taken every
`snapshot_interval` good steps — so a rollback replays from at most
`snapshot_interval - 1` steps back, and with the default interval of 1
the replay is exactly "this batch never happened".

Reports into the shared observability registry:
`paddle_resilience_rollbacks_total`,
`paddle_resilience_skipped_batches_total`, plus `bad_step` events.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from .. import observability as _obs
from .retry import RetryPolicy, call_with_retry

_tree = jax.tree_util


class SkipBudgetExhausted(RuntimeError):
    """More bad steps than the skip budget allows — the failure is not
    an isolated batch; stop instead of silently dropping the dataset."""


def _to_host(tree):
    return _tree.tree_map(
        lambda x: np.asarray(x) if hasattr(x, 'shape') else x, tree)


def _to_device(tree):
    return _tree.tree_map(
        lambda x: jnp.asarray(x) if hasattr(x, 'shape') else x, tree)


class FaultTolerantStep:
    """Wrap a train step with snapshot / bad-step rollback / retry.

    Args:
        step: the underlying step — `TrainStep`, `DistTrainStep`, or any
            callable. Step-shaped objects get automatic snapshot/restore
            of `(layer params+buffers, _opt_state, _n_calls)`.
        skip_budget: total bad steps the run may drop before
            `SkipBudgetExhausted` (default FLAGS_ft_skip_budget).
        snapshot_interval: good steps between host snapshots (default
            FLAGS_ft_snapshot_interval; 1 = snapshot before every step).
        spike_window / spike_sigma / spike_min_steps: LossSpikeDetector
            config; `check_spikes=False` reduces detection to NaN/Inf.
        retry_policy: RetryPolicy for transient *errors raised by* the
            step (PjRt hiccups); None disables retry.
        watchdog: an armed `StepWatchdog` whose watch() brackets each
            step call; None disables.
    """

    def __init__(self, step: Callable, *, skip_budget: Optional[int] = None,
                 snapshot_interval: Optional[int] = None,
                 spike_window: int = 20, spike_sigma: float = 6.0,
                 spike_min_steps: int = 5, check_spikes: bool = True,
                 retry_policy: Optional[RetryPolicy] = None,
                 watchdog=None,
                 snapshot_fn: Optional[Callable[[], Any]] = None,
                 restore_fn: Optional[Callable[[Any], None]] = None):
        self.step = step
        self.skip_budget = int(_flags.flag('FLAGS_ft_skip_budget')
                               if skip_budget is None else skip_budget)
        self.snapshot_interval = max(1, int(
            _flags.flag('FLAGS_ft_snapshot_interval')
            if snapshot_interval is None else snapshot_interval))
        self.retry_policy = retry_policy
        self.watchdog = watchdog
        self._snapshot_fn = snapshot_fn
        self._restore_fn = restore_fn
        if snapshot_fn is None and not hasattr(step, 'layer'):
            raise TypeError(
                f'{type(step).__name__} is not step-shaped (no .layer); '
                f'pass explicit snapshot_fn/restore_fn')
        self._spikes = None
        if check_spikes:
            from ..debug import LossSpikeDetector
            self._spikes = LossSpikeDetector(
                window=spike_window, threshold_sigma=spike_sigma,
                min_steps=spike_min_steps)
        self._snapshot = None
        self._since_snapshot = 0
        self.rollbacks = 0
        self.skipped_batches = 0
        self.good_steps = 0
        self.last_step_skipped = False

    # -- state capture ------------------------------------------------------
    def _capture(self):
        if self._snapshot_fn is not None:
            return self._snapshot_fn()
        layer = self.step.layer
        return {
            'params': {n: np.asarray(p.value)
                       for n, p in layer.named_parameters()},
            'buffers': {n: np.asarray(b.value)
                        for n, b in layer.named_buffers()},
            'opt': _to_host(getattr(self.step, '_opt_state', None)),
            'n_calls': int(getattr(self.step, '_n_calls', 0)),
        }

    def _restore(self, snap):
        if self._restore_fn is not None:
            self._restore_fn(snap)
            return
        layer = self.step.layer
        pmap = dict(layer.named_parameters())
        for n, v in snap['params'].items():
            pmap[n]._data = jnp.asarray(v)
            pmap[n]._node = None
        bmap = dict(layer.named_buffers())
        for n, v in snap['buffers'].items():
            bmap[n]._data = jnp.asarray(v)
        if hasattr(self.step, '_opt_state'):
            self.step._opt_state = _to_device(snap['opt'])
        if hasattr(self.step, '_n_calls'):
            self.step._n_calls = snap['n_calls']

    # -- the wrapped step ---------------------------------------------------
    def _run(self, *args, **kwargs):
        ctx = self.watchdog.watch() if self.watchdog is not None else None
        if ctx is None:
            return self.step(*args, **kwargs)
        with ctx:
            return self.step(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        self.last_step_skipped = False
        if self._snapshot is None \
                or self._since_snapshot >= self.snapshot_interval:
            self._snapshot = self._capture()
            self._since_snapshot = 0
        if self.retry_policy is not None:
            loss = call_with_retry(self._run, *args,
                                   policy=self.retry_policy,
                                   site='train_step', **kwargs)
        else:
            loss = self._run(*args, **kwargs)
        lv = float(loss.numpy()) if hasattr(loss, 'numpy') else float(
            np.asarray(loss))
        bad = self._spikes.update(lv) if self._spikes is not None \
            else not math.isfinite(lv)
        if bad:
            self.rollbacks += 1
            self.skipped_batches += 1
            if _obs.enabled():
                reg = _obs.get_registry()
                reg.counter('paddle_resilience_rollbacks_total',
                            'bad-step rollbacks to the last snapshot').inc()
                reg.counter('paddle_resilience_skipped_batches_total',
                            'batches dropped by bad-step handling').inc()
                _obs.emit('bad_step', loss=lv,
                          skipped=self.skipped_batches,
                          budget=self.skip_budget)
            # spanned so the restore cost books as `rollback` in the
            # goodput ledger (which ALSO moves the bad step's compute
            # there on the `bad_step` event emitted above)
            with _obs.span('resilience.rollback',
                           skipped=self.skipped_batches):
                self._restore(self._snapshot)
            self.last_step_skipped = True
            if self.skipped_batches > self.skip_budget:
                # flight-recorder trigger: the postmortem bundle is on
                # disk BEFORE the run dies on the raise below
                _obs.emit('skip_budget_exhausted', loss=lv,
                          skipped=self.skipped_batches,
                          budget=self.skip_budget)
                raise SkipBudgetExhausted(
                    f'{self.skipped_batches} bad steps exceed the skip '
                    f'budget of {self.skip_budget} (last loss {lv})')
        else:
            self.good_steps += 1
            self._since_snapshot += 1
        return loss

    def stats(self) -> Dict[str, Any]:
        return {'good_steps': self.good_steps,
                'rollbacks': self.rollbacks,
                'skipped_batches': self.skipped_batches,
                'skip_budget': self.skip_budget,
                'snapshot_interval': self.snapshot_interval}

    # look like the wrapped step (Model.fit pokes at ._opt_state etc.)
    def __getattr__(self, name):
        return getattr(self.step, name)
